"""SARATHI-style mixed batches (ISSUE 11): chunked prefill fused into the
live decode step.

The tier-1 mixed gate: greedy outputs must be token-identical with
``LMRS_MIXED=0`` vs ``1`` across prefix-cache on/off and speculation
on/off (interpret mode runs the real ragged multi-token kernel), the
fused dispatcher must actually run (piggybacked-token accounting), decode
cadence must continue through an admission burst, and the scheduler
auditor must stay clean."""

from __future__ import annotations

import numpy as np
import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


def kernel_model():
    # hd = 128: the ragged kernel gate is on under LMRS_FORCE_KERNELS
    return ModelConfig(vocab_size=512, dim=512, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=256, max_seq_len=512,
                       dtype="float32")


def _cfg(mixed: bool, *, prefix_cache: bool = True, spec_k: int = 0,
         slots: int = 2, **kw) -> EngineConfig:
    # decode_block small so admissions land while earlier requests still
    # decode — the regime mixed dispatch exists for
    base = dict(backend="jax", scheduler="continuous", max_tokens=16,
                max_batch_slots=slots, seed=0, decode_block=3,
                prefill_chunk=64, prefix_cache=prefix_cache,
                speculate_k=spec_k, mixed_batch=mixed)
    base.update(kw)
    return EngineConfig(**base)


def _mix_requests(n: int = 4) -> list[GenerationRequest]:
    """Shared-preamble mix of short + long prompts: long prompts chunk,
    short ones decode through the admissions, preambles collide in the
    prefix cache at page boundaries."""
    pre = "shared mixed preamble alpha beta "
    reqs = []
    for i in range(n):
        body = (f"request {i} " + "lorem ipsum dolor sit amet " * (1 + 5 * (i % 2)))
        reqs.append(GenerationRequest(
            prompt=(pre if i % 2 else "") + body, request_id=i,
            temperature=0.0, max_new_tokens=12 + i))
    return reqs


def _run(cfg: EngineConfig, mc, reqs):
    eng = JaxEngine(cfg, mc)
    out = eng.generate_batch(reqs)
    sched = eng._scheduler
    assert sched.audit() == []
    texts = [(r.text, r.finish_reason, r.completion_tokens) for r in out]
    assert all(r.error is None for r in out)
    m = dict(sched.metrics)
    eng.shutdown()
    return texts, m


@pytest.mark.parametrize("prefix_cache", [True, False])
@pytest.mark.parametrize("spec_k", [0, 3])
def test_mixed_greedy_identity_matrix(monkeypatch, prefix_cache, spec_k):
    """LMRS_MIXED=0 vs 1 token identity across the prefix-cache x
    speculation matrix (the ISSUE 11 acceptance bar).  The mixed arm must
    actually exercise the fused dispatcher — an identity proven on runs
    that never mixed proves nothing."""
    mc = tiny_model()
    reqs = _mix_requests()
    monkeypatch.setenv("LMRS_MIXED", "0")
    want, m_off = _run(_cfg(True, prefix_cache=prefix_cache,
                            spec_k=spec_k), mc, reqs)
    assert m_off["mixed_dispatches"] == 0  # kill switch really off
    monkeypatch.setenv("LMRS_MIXED", "1")
    got, m_on = _run(_cfg(True, prefix_cache=prefix_cache,
                          spec_k=spec_k), mc, reqs)
    assert m_on["mixed_dispatches"] > 0, "mixed path not exercised"
    assert m_on["prefill_tokens_piggybacked"] > 0
    assert got == want


@pytest.mark.parametrize("spec_k", [0, 3])
def test_mixed_identity_on_interpret_kernels(monkeypatch, spec_k):
    """The same A/B through the REAL ragged multi-token row-group kernel
    (interpret mode): mixed steps dispatch [B, T] batches where decode
    rows carry one real token and the prefill row its slice — the kernel
    must survive (no silent XLA fallback) and outputs must match the
    alternating path exactly."""
    monkeypatch.setenv("LMRS_FORCE_KERNELS", "interpret")
    mc = kernel_model()
    reqs = [GenerationRequest(prompt="short kernel probe", request_id=0,
                              temperature=0.0, max_new_tokens=9),
            GenerationRequest(prompt="mixed kernel probe words " * 14,
                              request_id=1, temperature=0.0,
                              max_new_tokens=9),
            GenerationRequest(prompt="third staggered prompt " * 6,
                              request_id=2, temperature=0.0,
                              max_new_tokens=9)]
    cfg = lambda mixed: _cfg(mixed, spec_k=spec_k, max_tokens=9)
    monkeypatch.setenv("LMRS_MIXED", "0")
    off = JaxEngine(cfg(True), mc)
    assert off._scheduler._use_ragged
    want = [r.text for r in off.generate_batch(reqs)]
    off.shutdown()
    monkeypatch.setenv("LMRS_MIXED", "1")
    on = JaxEngine(cfg(True), mc)
    got = [r.text for r in on.generate_batch(reqs)]
    sched = on._scheduler
    assert sched.metrics["mixed_dispatches"] > 0, "mixed path not exercised"
    assert sched._use_ragged, "multi-token kernel silently degraded"
    # RPA (the default) compiles span programs; LMRS_RPA=0 the legacy
    # mixed family — either way a fused shape must actually have built
    assert sched._rpa_fns or sched._mixed_fns, "no mixed shape compiled"
    assert sched.audit() == []
    on.shutdown()
    assert got == want


def test_mixed_decode_cadence_through_admission_burst():
    """A long prompt admitted mid-decode must NOT pause the live decode
    rows: its prefill rides the decode steps as budget-clipped slices
    (piggybacked tokens cover the whole prompt) and the decode rows keep
    emitting between the admission and prefill completion."""
    mc = tiny_model()
    eng = JaxEngine(_cfg(True, slots=2, prefill_chunk=4096,
                         mixed_token_budget=64, max_tokens=24), mc)
    sched = eng._scheduler
    burst: list[GenerationRequest] = [
        # staggered budgets: request 1 finishes early, freeing the slot
        # for the burst admission WHILE request 0 still decodes
        GenerationRequest(prompt="steady decoder", request_id=0,
                          temperature=0.0, max_new_tokens=24),
        GenerationRequest(prompt="second steady", request_id=1,
                          temperature=0.0, max_new_tokens=6),
        # admitted when a slot frees, while the other still decodes: the
        # prompt (~190 tokens) exceeds the 64-token step budget, so its
        # prefill MUST split over several mixed steps
        GenerationRequest(prompt="burst admission prompt words " * 7,
                          request_id=2, temperature=0.0, max_new_tokens=4),
    ]
    out = eng.generate_batch(burst)
    assert all(r.error is None for r in out)
    m = sched.metrics
    assert m["mixed_dispatches"] >= 3, m  # sliced across several steps
    # the burst prompt's prefill rode decode steps, not dedicated waves
    burst_tokens = len(sched._encode(burst[2])[0])
    assert m["prefill_tokens_piggybacked"] >= burst_tokens
    rep = sched.metrics_report()["mixed_batch"]
    assert rep["enabled"] and rep["dispatches"] == m["mixed_dispatches"]
    assert 0.0 < rep["fill_ratio"] <= 1.0
    # decode rows advanced during the mixed window: every mixed dispatch
    # emitted one token per live decode row
    assert m["decode_tokens"] >= m["mixed_dispatches"]
    assert sched.audit() == []
    eng.shutdown()


def test_mixed_metrics_and_report_shape():
    """The mixed_batch report block and the windowable metric keys bench
    relies on (mixed_dispatches / mixed_fill_sum /
    prefill_tokens_piggybacked) exist and stay consistent."""
    mc = tiny_model()
    eng = JaxEngine(_cfg(True), mc)
    eng.generate_batch(_mix_requests())
    m = eng._scheduler.metrics
    rep = eng._scheduler.metrics_report()
    blk = rep["mixed_batch"]
    assert blk["dispatches"] == m["mixed_dispatches"]
    assert blk["prefill_tokens_piggybacked"] == m["prefill_tokens_piggybacked"]
    assert blk["token_budget"] == 256
    if m["mixed_dispatches"]:
        assert 0.0 < blk["fill_ratio"] <= 1.0
        assert m["prefill_tokens_piggybacked"] <= m["prefill_tokens"]
    # the block-gap scope label (docs/PERF.md): batch waves vs serving
    # cadence must be distinguishable from the report alone
    assert "decode_block_gap_scope" in rep
    eng.shutdown()


def test_mixed_gated_off_under_int8_kv(monkeypatch):
    """LEGACY dispatch (LMRS_RPA=0): kv_quantize=int8 cannot own a mixed
    chunk's prefill scales through the [B, T] fused path, so the
    dispatcher must disarm itself (and say so in the report)."""
    monkeypatch.setenv("LMRS_RPA", "0")
    mc = tiny_model()
    eng = JaxEngine(_cfg(True, page_size=32, kv_quantize="int8",
                         prefix_cache=False), mc)
    assert not eng._scheduler._mixed
    assert eng._scheduler.metrics_report()["mixed_batch"]["enabled"] is False
    out = eng.generate_batch(_mix_requests(2))
    assert all(r.error is None for r in out)
    assert eng._scheduler.metrics["mixed_dispatches"] == 0
    eng.shutdown()


def test_mixed_int8_kv_armed_under_rpa(monkeypatch):
    """The retired composition gate (ISSUE 16): under ragged span
    dispatch int8 KV x mixed RUNS — per-row frozen scales ride the span
    descriptor (a fresh-start slice owns its slot's scales, every other
    row clamps) — with greedy token identity against the int8
    alternating path and a clean audit."""
    mc = tiny_model()
    reqs = _mix_requests()
    cfg = lambda mixed: _cfg(mixed, page_size=32, kv_quantize="int8",
                             prefix_cache=False)
    monkeypatch.setenv("LMRS_MIXED", "0")
    want, m_off = _run(cfg(True), mc, reqs)
    assert m_off["mixed_dispatches"] == 0
    monkeypatch.setenv("LMRS_MIXED", "1")
    got, m_on = _run(cfg(True), mc, reqs)
    assert m_on["mixed_dispatches"] > 0, "int8 x mixed not exercised"
    assert m_on["rpa_dispatches"] > 0
    assert got == want


def test_mixed_budget_floor_falls_back_to_alternating():
    """A budget the decode rows nearly exhaust leaves no room for a
    slice: the step must fall back to alternating dispatch (progress,
    never a degenerate 1-token slice loop)."""
    mc = tiny_model()
    # budget 32 (config floor) with 24 slots leaves < 16 slice tokens
    # whenever >= 17 rows decode; with 2 slots it mixes normally — use a
    # wide engine so the floor actually binds
    eng = JaxEngine(_cfg(True, slots=24, mixed_token_budget=32,
                         max_tokens=8), mc)
    reqs = [GenerationRequest(prompt=f"floor probe {i} " * 3, request_id=i,
                              temperature=0.0, max_new_tokens=8)
            for i in range(30)]
    out = eng.generate_batch(reqs)
    assert all(r.error is None for r in out)
    assert eng._scheduler.audit() == []
    eng.shutdown()


def test_mock_engine_mixed_block(monkeypatch):
    """The no-device arm exposes the same knob surface: mixed accounting
    appears in engine_metrics(), and the LMRS_MIXED kill switch disarms
    it (serving/jobs CI asserts knob parity without a device)."""
    from lmrs_tpu.engine.mock import MockEngine

    reqs = [GenerationRequest(prompt="one " * 30, request_id=0),
            GenerationRequest(prompt="two " * 50, request_id=1),
            GenerationRequest(prompt="three " * 20, request_id=2)]
    eng = MockEngine(mixed_token_budget=64)
    assert eng.generate_batch(reqs)
    blk = eng.engine_metrics()["mixed_batch"]
    assert blk["enabled"] and blk["dispatches"] > 0
    assert blk["prefill_tokens_piggybacked"] > 0
    assert 0.0 < blk["fill_ratio"] <= 1.0
    # deterministic emulation: same batch, same counters
    eng2 = MockEngine(mixed_token_budget=64)
    eng2.generate_batch(reqs)
    assert eng2.engine_metrics() == eng.engine_metrics()
    monkeypatch.setenv("LMRS_MIXED", "0")
    off = MockEngine(mixed_token_budget=64)
    off.generate_batch(reqs)
    # mixed accounting absent when disarmed (the cost/slo parity blocks
    # report regardless — they bill every request, mixed or not)
    assert "mixed_batch" not in off.engine_metrics()


def test_make_engine_threads_mixed_knobs():
    """EngineConfig.mixed_* reach the mock through make_engine (the same
    config path the serving CLI uses)."""
    from lmrs_tpu.engine.api import make_engine

    eng = make_engine(EngineConfig(backend="mock", mixed_batch=True,
                                   mixed_token_budget=128))
    assert eng.mixed_batch and eng.mixed_token_budget == 128
    off = make_engine(EngineConfig(backend="mock", mixed_batch=False))
    assert not off.mixed_batch


def test_mixed_streaming_deltas_concatenate_exactly():
    """on_tokens deltas emitted across mixed steps must concatenate to
    the final text (the per-block streaming contract survives the fused
    dispatch path)."""
    mc = tiny_model()
    eng = JaxEngine(_cfg(True), mc)
    deltas: dict[int, str] = {}

    def on_tokens(rid, text):
        deltas[rid] = deltas.get(rid, "") + text

    out = eng.generate_batch(_mix_requests(), on_tokens=on_tokens)
    assert eng._scheduler.metrics["mixed_dispatches"] > 0
    for r in out:
        assert deltas.get(r.request_id, "") == r.text
    eng.shutdown()
