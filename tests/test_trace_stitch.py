"""Fleet-wide trace stitching gate (ISSUE 8 tentpole b + acceptance 3).

A REAL two-process prefill-role + decode-role topology (lmrs-serve OS
processes, mock backend, LMRS_TRACE=1) serves a disaggregated request
through the pool-aware router; the router then pulls each pod's
``GET /v1/trace`` page and stitches them (obs.stitch_traces).  Asserted:

* the merged file passes ``validate_trace_file`` (the same schema gate
  CI runs on single-host traces, now including the handoff-instant
  contract args);
* the request appears as exactly ONE stitched causal chain under ONE
  trace id — spans from BOTH pods, with the prefill pod's
  ``handoff_export`` strictly before the decode pod's
  ``handoff_import`` and a terminal ``finish``;
* the local ``/v1/trace`` endpoint answers per host, and 409s when
  tracing is off.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.obs import stitched_chains, validate_trace_file
from lmrs_tpu.serving.router import RouterEngine

_PROMPT = ("Transcript section: The committee reviewed the budget at "
           "length. Afterwards the chair summarized the next steps for "
           "the quarter in detail. Finally the group agreed to reconvene "
           "on Tuesday to close the remaining items.")


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_worker(port: int, role: str) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu", LMRS_TRACE="1")
    return subprocess.Popen(
        [sys.executable, "-m", "lmrs_tpu.serving.cli",
         "--backend", "mock", "--port", str(port), "--role", role, "-q"],
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _wait_healthy(url: str, proc, deadline_s: float = 60.0) -> None:
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if proc.poll() is not None:
            raise RuntimeError(
                f"worker died rc={proc.returncode}: "
                f"{proc.stderr.read().decode()[-2000:]}")
        try:
            with urllib.request.urlopen(f"{url}/healthz", timeout=2) as r:
                if r.status == 200:
                    return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"{url} never became healthy")


@pytest.fixture(scope="module")
def traced_topology():
    """prefill-role + decode-role lmrs-serve processes with the
    in-process tracer armed (LMRS_TRACE=1)."""
    ports = [free_port(), free_port()]
    procs = [_spawn_worker(ports[0], "prefill"),
             _spawn_worker(ports[1], "decode")]
    try:
        for port, proc in zip(ports, procs):
            _wait_healthy(f"http://127.0.0.1:{port}", proc)
        yield ports
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_two_process_disagg_stitches_to_single_causal_chain(
        traced_topology, tmp_path):
    """The tier-1 stitch gate: one disaggregated request → one merged
    Perfetto file → one causally ordered span chain under one trace id."""
    ports = traced_topology
    router = RouterEngine([], prefill_hosts=[f"127.0.0.1:{ports[0]}"],
                          decode_hosts=[f"127.0.0.1:{ports[1]}"])
    try:
        res = router.generate_batch([GenerationRequest(
            prompt=_PROMPT, request_id=0, temperature=0.0)])[0]
        assert res.error is None and res.text
        assert router._handoffs == 1 and router._handoff_fallbacks == 0

        doc = router.stitched_trace()
        assert doc["stitch"]["unreachable"] == []
        assert set(doc["stitch"]["hosts"]) == {
            f"127.0.0.1:{p}" for p in ports}
        out = tmp_path / "stitched.json"
        out.write_text(json.dumps(doc), encoding="utf-8")
        events = validate_trace_file(out)  # the CI schema gate

        chains = stitched_chains(events)
        assert len(chains) == 1, f"want ONE trace id, got {list(chains)}"
        (trace_id, chain), = chains.items()
        assert trace_id == doc["stitch"]["traces"][0]
        names = [e["name"] for e in chain]
        hosts = {e["name"]: e["args"]["host"] for e in chain}
        # causal order: export (pod A) strictly before import (pod B),
        # terminal finish present, timestamps monotonic
        assert "handoff_export" in names and "handoff_import" in names
        assert names.index("handoff_export") < names.index("handoff_import")
        assert hosts["handoff_export"] == f"127.0.0.1:{ports[0]}"
        assert hosts["handoff_import"] == f"127.0.0.1:{ports[1]}"
        assert names[-1] == "finish"
        ts = [e["ts"] for e in chain]
        assert ts == sorted(ts)
        # spans from BOTH pods landed on the one stitched track
        assert {e["args"]["host"] for e in chain} == {
            f"127.0.0.1:{p}" for p in ports}
    finally:
        router.shutdown()


def test_per_host_trace_endpoint(traced_topology):
    """Each pod's GET /v1/trace serves its own Chrome-trace document
    (the page the router-side stitcher pulls)."""
    port = traced_topology[0]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/trace", timeout=5) as r:
        doc = json.loads(r.read())
    assert "traceEvents" in doc and doc.get("host", "").endswith(str(port))
    assert isinstance(doc.get("clock_s"), float)


def test_trace_endpoint_409_when_tracing_off():
    """A host without LMRS_TRACE answers 409 with a clear arming hint —
    never an empty 200 the stitcher would silently merge as 'no spans'."""
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.obs import disable_tracing
    from lmrs_tpu.serving.server import EngineHTTPServer

    disable_tracing()  # other tests may have armed the process tracer
    server = EngineHTTPServer(MockEngine(), port=0)
    server.start_background()
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://{server.host}:{server.port}/v1/trace", timeout=5)
        assert exc.value.code == 409
        assert "LMRS_TRACE" in json.loads(exc.value.read())[
            "error"]["message"]
    finally:
        server.shutdown()


def test_debug_profile_endpoint_501_without_device_engine():
    """POST /v1/debug/profile needs the jax engine's profiler hook; the
    mock backend answers 501 (capability, not a crash)."""
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    server = EngineHTTPServer(MockEngine(), port=0)
    server.start_background()
    try:
        req = urllib.request.Request(
            f"http://{server.host}:{server.port}/v1/debug/profile",
            data=json.dumps({"duration_s": 0.5}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 501
    finally:
        server.shutdown()


def test_debug_profile_capture_jax(tmp_path):
    """The jax engine's debug_profile hook runs a bounded capture (CPU
    backend profiles too) and rejects a second concurrent capture."""
    jax = pytest.importorskip("jax")  # noqa: F841
    from lmrs_tpu.obs import profile_capture_active
    from lmrs_tpu.obs.perf import start_profile_capture

    ok, out = start_profile_capture(str(tmp_path / "prof"), duration_s=0.3)
    assert ok, out
    dup_ok, reason = start_profile_capture(str(tmp_path / "p2"), 0.3)
    assert not dup_ok and "already" in reason
    t0 = time.time()
    while profile_capture_active() and time.time() - t0 < 10:
        time.sleep(0.05)
    assert not profile_capture_active()
    assert any((tmp_path / "prof").rglob("*")), "no profile artifacts"
