"""Unified ragged-span dispatch (ISSUE 16): one kernel for every phase.

Two layers of contract:

* ops-level — ``ragged_spans_pallas`` (interpret mode) must reproduce the
  kernels it retires at their own shapes: the fused single-token decode
  kernel at q_len=1 spans, the multi-token verify kernel at q_len=T
  spans, and the ``ragged_spans_xla`` scatter+gather reference on mixed
  span lists (decode rows + a long prefill-slice row + inactive rows),
  bf16-free f32 inputs and int8 pools both.  Pool comparisons are
  restricted to each row's VALID prefix (positions < base + q_len): the
  span kernel's tile-padding tokens write garbage at FUTURE positions by
  the mixed path's existing convention, where the references park them
  on the null page.

* scheduler-level — greedy outputs must be token-identical with
  ``LMRS_RPA=0`` (legacy per-phase dispatch) vs ``1`` across the
  prefix-cache x speculation x int8-KV matrix, the kill switch must be
  byte-for-byte (legacy program caches populated, span caches empty),
  and the one-bucket-family claim must show up as a compile-shape count
  no larger than the legacy per-phase families for the same workload.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.ops.paged_attention import (
    pack_spans,
    paged_decode_pallas_fused,
    paged_decode_pallas_multi,
    ragged_spans_pallas,
    ragged_spans_xla,
)

# --------------------------------------------------------------- ops level


def _span_fixture(seed, q_lens, h=8, kh=4, hd=128, ps=16, n_pages=32,
                  width=3):
    """Flat span buffers + per-row pools/tables.  Every flat row gets
    random q/k/v — including the alignment-padding rows — so parity also
    proves the padding is masked, not merely zero."""
    b = len(q_lens)
    qs, total = pack_spans(np.asarray(q_lens, np.int32))
    rng = jax.random.split(jax.random.PRNGKey(seed), 5)
    qf = jax.random.normal(rng[0], (total, h, hd), jnp.float32)
    knf = jax.random.normal(rng[1], (total, kh, hd), jnp.float32)
    vnf = jax.random.normal(rng[2], (total, kh, hd), jnp.float32)
    k_pages = jax.random.normal(rng[3], (n_pages, kh, ps, hd), jnp.float32)
    v_pages = jax.random.normal(rng[4], (n_pages, kh, ps, hd), jnp.float32)
    tables = jnp.asarray(
        np.random.default_rng(seed).permutation(n_pages - 1)[: b * width]
        .reshape(b, width) + 1, jnp.int32)
    row_flat = np.full((total,), b, np.int32)
    for i, (s, l) in enumerate(zip(qs, q_lens)):
        row_flat[s:s + l] = i
    return qs, total, qf, knf, vnf, k_pages, v_pages, tables, row_flat


def _valid_windows(pool, tables, upto, ps):
    """Per-row gathered window prefix [upto[b], K, hd] — the region both
    implementations must agree on bit-for-bit (past it lies the span
    kernel's future-position padding garbage)."""
    win = np.asarray(pool)[np.asarray(tables)]          # [B, W, K, ps, hd]
    win = win.transpose(0, 1, 3, 2, 4).reshape(
        win.shape[0], -1, win.shape[2], win.shape[4])   # [B, W*ps, K, hd]
    return [win[i, :int(u)] for i, u in enumerate(np.asarray(upto))]


def _assert_pool_parity(got_pool, ref_pool, tables, upto, ps):
    for g, r in zip(_valid_windows(got_pool, tables, upto, ps),
                    _valid_windows(ref_pool, tables, upto, ps)):
        np.testing.assert_array_equal(g, r)


def test_rpa_decode_parity_vs_fused():
    """q_len=1 spans vs the retired fused single-token decode kernel:
    same attention outputs (each span's one real row) and same pool
    contents over every row's valid prefix.  Ragged bases including a
    fresh (base 0) row and an inactive (q_len=0) row."""
    q_lens = [1, 0, 1, 1, 1]
    bases = np.asarray([39, 0, 16, 47, 0], np.int32)
    ps = 16
    qs, total, qf, knf, vnf, kp, vp, tables, row_flat = _span_fixture(
        0, q_lens, ps=ps)

    got, k_out, v_out = ragged_spans_pallas(
        qf, knf, vnf, kp, vp, tables, jnp.asarray(bases),
        jnp.asarray(qs), jnp.asarray(q_lens, jnp.int32), interpret=True)

    # the fused kernel's kv_lens INCLUDE the written token; inactive = 0
    q1 = jnp.stack([qf[s] for s in qs])
    kn1 = jnp.stack([knf[s] for s in qs])
    vn1 = jnp.stack([vnf[s] for s in qs])
    fused_lens = jnp.asarray(
        [b + l for b, l in zip(bases, q_lens)], jnp.int32)
    want, k_ref, v_ref = paged_decode_pallas_fused(
        q1, kn1, vn1, kp, vp, tables, fused_lens, interpret=True)

    for i, l in enumerate(q_lens):
        if l:
            np.testing.assert_allclose(np.asarray(got[qs[i]]),
                                       np.asarray(want[i]),
                                       rtol=2e-5, atol=2e-5)
    upto = bases + np.asarray(q_lens)
    _assert_pool_parity(k_out, k_ref, tables, upto, ps)
    _assert_pool_parity(v_out, v_ref, tables, upto, ps)


def test_rpa_verify_parity_vs_multi():
    """q_len=T spans vs the retired multi-token verify kernel: all T
    per-token outputs and the written span, across page-straddling,
    in-page, window-straddling, and fresh (base 0) rows."""
    t, ps = 3, 16
    bases = np.asarray([15, 3, 32, 0], np.int32)
    q_lens = [t] * 4
    qs, total, qf, knf, vnf, kp, vp, tables, row_flat = _span_fixture(
        1, q_lens, ps=ps)

    got, k_out, v_out = ragged_spans_pallas(
        qf, knf, vnf, kp, vp, tables, jnp.asarray(bases),
        jnp.asarray(qs), jnp.asarray(q_lens, jnp.int32), interpret=True)

    qm = jnp.stack([qf[s:s + t] for s in qs])       # [B, T, H, hd]
    knm = jnp.stack([knf[s:s + t] for s in qs])
    vnm = jnp.stack([vnf[s:s + t] for s in qs])
    multi_lens = jnp.asarray(bases + t, jnp.int32)  # includes the T tokens
    want, k_ref, v_ref = paged_decode_pallas_multi(
        qm, knm, vnm, kp, vp, tables, multi_lens, interpret=True)

    for i, s in enumerate(qs):
        np.testing.assert_allclose(np.asarray(got[s:s + t]),
                                   np.asarray(want[i]),
                                   rtol=2e-5, atol=2e-5)
    upto = bases + t
    _assert_pool_parity(k_out, k_ref, tables, upto, ps)
    _assert_pool_parity(v_out, v_ref, tables, upto, ps)


def test_rpa_mixed_spans_match_xla_reference():
    """A genuinely MIXED span list — decode rows, a long prefill-slice
    row whose length is not a SPAN_QT multiple, and an inactive row —
    against the scatter+gather reference (the sp>1 / CPU-fallback path):
    in-span outputs agree and pools agree over every valid prefix."""
    q_lens = [1, 13, 1, 0]
    bases = np.asarray([20, 7, 0, 0], np.int32)
    ps = 16
    qs, total, qf, knf, vnf, kp, vp, tables, row_flat = _span_fixture(
        2, q_lens, ps=ps)

    got, k_out, v_out = ragged_spans_pallas(
        qf, knf, vnf, kp, vp, tables, jnp.asarray(bases),
        jnp.asarray(qs), jnp.asarray(q_lens, jnp.int32), interpret=True)
    want, k_ref, v_ref = ragged_spans_xla(
        qf, knf, vnf, kp, vp, tables, jnp.asarray(bases),
        jnp.asarray(qs), jnp.asarray(q_lens, jnp.int32),
        jnp.asarray(row_flat))

    in_span = row_flat < len(q_lens)
    np.testing.assert_allclose(np.asarray(got)[in_span],
                               np.asarray(want)[in_span],
                               rtol=2e-5, atol=2e-5)
    upto = bases + np.asarray(q_lens)
    _assert_pool_parity(k_out, k_ref, tables, upto, ps)
    _assert_pool_parity(v_out, v_ref, tables, upto, ps)


def test_rpa_mixed_spans_int8_parity():
    """The same mixed span list over int8 pools (the composition the
    legacy dispatcher forbade): per-token quantization through the span
    RMW must reproduce the XLA reference bit-for-bit over every valid
    prefix, and the dequantized walk must agree on in-span outputs."""
    q_lens = [1, 13, 1, 0]
    bases = np.asarray([20, 7, 0, 0], np.int32)
    b, kh, hd, ps, n_pages = 4, 4, 128, 64, 12
    qs, total, qf, knf, vnf, _, _, _, row_flat = _span_fixture(
        3, q_lens, ps=ps, n_pages=n_pages, width=2)
    rng = np.random.default_rng(3)
    kq = jnp.asarray(rng.integers(-127, 128, (n_pages, kh, ps, hd)),
                     jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (n_pages, kh, ps, hd)),
                     jnp.int8)
    tables = jnp.asarray(rng.permutation(n_pages - 1)[: b * 2]
                         .reshape(b, 2) + 1, jnp.int32)
    ks = jnp.asarray(rng.uniform(0.01, 0.05, (b, kh, hd)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.05, (b, kh, hd)), jnp.float32)

    got, k_out, v_out = ragged_spans_pallas(
        qf, knf, vnf, kq, vq, tables, jnp.asarray(bases),
        jnp.asarray(qs), jnp.asarray(q_lens, jnp.int32), interpret=True,
        kscale=ks, vscale=vs)
    want, k_ref, v_ref = ragged_spans_xla(
        qf, knf, vnf, kq, vq, tables, jnp.asarray(bases),
        jnp.asarray(qs), jnp.asarray(q_lens, jnp.int32),
        jnp.asarray(row_flat), kv_scales=(ks, vs))

    in_span = row_flat < b
    np.testing.assert_allclose(np.asarray(got)[in_span],
                               np.asarray(want)[in_span],
                               rtol=2e-5, atol=2e-5)
    upto = bases + np.asarray(q_lens)
    _assert_pool_parity(k_out, k_ref, tables, upto, ps)
    _assert_pool_parity(v_out, v_ref, tables, upto, ps)


# --------------------------------------------------------- scheduler level


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


def _cfg(**kw) -> EngineConfig:
    base = dict(backend="jax", scheduler="continuous", max_tokens=16,
                max_batch_slots=2, seed=0, decode_block=3,
                prefill_chunk=64, mixed_batch=True)
    base.update(kw)
    return EngineConfig(**base)


def _mix_requests(n: int = 4) -> list[GenerationRequest]:
    pre = "shared span preamble alpha beta "
    reqs = []
    for i in range(n):
        body = (f"request {i} " + "span probe words here " * (1 + 5 * (i % 2)))
        reqs.append(GenerationRequest(
            prompt=(pre if i % 2 else "") + body, request_id=i,
            temperature=0.0, max_new_tokens=12 + i))
    return reqs


def _run(cfg: EngineConfig, mc, reqs):
    """Returns (texts, metrics, program-cache key sets) for one engine
    run; audits clean."""
    eng = JaxEngine(cfg, mc)
    out = eng.generate_batch(reqs)
    sched = eng._scheduler
    assert sched.audit() == []
    assert all(r.error is None for r in out)
    texts = [(r.text, r.finish_reason, r.completion_tokens) for r in out]
    m = dict(sched.metrics)
    caches = {"rpa": set(sched._rpa_fns),
              "mixed": set(sched._mixed_fns),
              "window": set(sched._prefill_window_fns),
              "decode": set(sched._decode_fns)}
    eng.shutdown()
    return texts, m, caches


@pytest.mark.parametrize("prefix_cache", [True, False])
@pytest.mark.parametrize("spec_k", [0, 3])
def test_rpa_greedy_identity_matrix(monkeypatch, prefix_cache, spec_k):
    """LMRS_RPA=0 vs 1 greedy token identity across prefix-cache x
    speculation with mixed batches armed — the ISSUE 16 acceptance bar.
    The span arm must actually dispatch span programs."""
    mc = tiny_model()
    reqs = _mix_requests()
    cfg = lambda: _cfg(prefix_cache=prefix_cache, speculate_k=spec_k)
    monkeypatch.setenv("LMRS_RPA", "0")
    want, m_off, _ = _run(cfg(), mc, reqs)
    assert m_off["rpa_dispatches"] == 0  # kill switch really off
    monkeypatch.setenv("LMRS_RPA", "1")
    got, m_on, _ = _run(cfg(), mc, reqs)
    assert m_on["rpa_dispatches"] > 0, "span path not exercised"
    assert got == want


@pytest.mark.parametrize("spec_k", [0, 3])
def test_rpa_greedy_identity_int8_kv(monkeypatch, spec_k):
    """The forbidden compositions, armed: int8 KV x mixed (x spec) runs
    through the span path with greedy outputs identical to the legacy
    per-phase dispatch of the same int8 engine."""
    mc = tiny_model()
    reqs = _mix_requests()
    cfg = lambda: _cfg(page_size=32, kv_quantize="int8",
                       prefix_cache=False, speculate_k=spec_k)
    monkeypatch.setenv("LMRS_RPA", "0")
    want, m_off, _ = _run(cfg(), mc, reqs)
    assert m_off["rpa_dispatches"] == 0
    monkeypatch.setenv("LMRS_RPA", "1")
    got, m_on, _ = _run(cfg(), mc, reqs)
    assert m_on["rpa_dispatches"] > 0, "int8 span path not exercised"
    assert m_on["mixed_dispatches"] > 0, "int8 x mixed not armed"
    assert got == want


def test_rpa_killswitch_byte_for_byte(monkeypatch):
    """LMRS_RPA=0 restores the legacy dispatch layer wholesale: no span
    program compiles, the legacy mixed family compiles instead, and the
    outputs match the span arm byte for byte."""
    mc = tiny_model()
    reqs = _mix_requests()
    monkeypatch.setenv("LMRS_RPA", "0")
    want, m_off, c_off = _run(_cfg(), mc, reqs)
    assert not c_off["rpa"], "legacy arm compiled a span program"
    assert c_off["mixed"], "legacy mixed family did not compile"
    assert m_off["rpa_compile_shapes"] == 0
    monkeypatch.setenv("LMRS_RPA", "1")
    got, m_on, c_on = _run(_cfg(), mc, reqs)
    assert c_on["rpa"], "span arm compiled no span program"
    assert not c_on["mixed"], "span arm still compiled legacy mixed fns"
    assert m_on["rpa_compile_shapes"] == len(c_on["rpa"])
    assert got == want


def test_rpa_compile_shapes_do_not_exceed_legacy(monkeypatch):
    """One bucket family: for the same workload the span arm's distinct
    compiled program count must not exceed the legacy per-phase families
    it replaces (mixed [t,w] + prefill-window [s,w]), and the span
    metric must report real span tokens."""
    mc = tiny_model()
    reqs = _mix_requests(6)
    monkeypatch.setenv("LMRS_RPA", "0")
    _, m_off, c_off = _run(_cfg(max_batch_slots=3), mc, reqs)
    legacy = len(c_off["mixed"]) + len(c_off["window"])
    assert legacy > 0, "workload never exercised the retired families"
    monkeypatch.setenv("LMRS_RPA", "1")
    _, m_on, c_on = _run(_cfg(max_batch_slots=3), mc, reqs)
    assert 0 < len(c_on["rpa"]) <= legacy
    assert m_on["rpa_span_tokens"] > 0
    assert m_on["rpa_span_tokens"] >= m_on["rpa_dispatches"]


def test_rpa_report_block_shape():
    """The windowed ``rpa`` report block bench/serving_latency consume:
    keys exist, dispatch counts agree with the counters, compile_shapes
    stays cumulative."""
    mc = tiny_model()
    eng = JaxEngine(_cfg(), mc)
    eng.generate_batch(_mix_requests())
    sched = eng._scheduler
    m = sched.metrics
    blk = sched.metrics_report()["rpa"]
    assert blk["enabled"] is True
    assert blk["dispatches"] == m["rpa_dispatches"]
    assert blk["span_tokens"] == m["rpa_span_tokens"]
    assert blk["compile_shapes"] == m["rpa_compile_shapes"]
    eng.shutdown()


def test_mock_engine_rpa_block(monkeypatch):
    """No-device knob parity: the mock exposes the same ``rpa`` metrics
    block and the LMRS_RPA kill switch disarms it."""
    from lmrs_tpu.engine.mock import MockEngine

    reqs = [GenerationRequest(prompt="one " * 30, request_id=0),
            GenerationRequest(prompt="two " * 50, request_id=1),
            GenerationRequest(prompt="three " * 20, request_id=2)]
    eng = MockEngine(mixed_token_budget=64)
    assert eng.generate_batch(reqs)
    blk = eng.engine_metrics()["rpa"]
    assert blk["enabled"] and blk["dispatches"] > 0
    assert blk["span_tokens"] >= blk["dispatches"]
    assert blk["compile_shapes"] >= 1
    monkeypatch.setenv("LMRS_RPA", "0")
    off = MockEngine(mixed_token_budget=64)
    off.generate_batch(reqs)
    assert "rpa" not in off.engine_metrics()
