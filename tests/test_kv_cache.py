"""Paged KV cache + allocator tests, and paged-vs-dense numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.engine.kv_cache import OutOfPages, PageAllocator, PagedKVCache
from lmrs_tpu.ops.paged_attention import paged_decode_pallas, paged_decode_xla


def test_allocator_alloc_free_cycle():
    a = PageAllocator(8)
    assert a.free_count == 7  # page 0 reserved (null page)
    p1 = a.alloc(3)
    assert len(set(p1)) == 3
    assert 0 not in p1
    assert a.free_count == 4
    a.free(p1)
    assert a.free_count == 7


def test_allocator_exhaustion():
    a = PageAllocator(4)
    a.alloc(3)
    with pytest.raises(OutOfPages):
        a.alloc(2)


def test_allocator_rejects_bad_free():
    a = PageAllocator(4)
    with pytest.raises(ValueError):
        a.free([99])
    with pytest.raises(ValueError):
        a.free([0])  # reserved null page may never be freed


def test_allocator_rejects_double_free():
    """Freeing a page already on the free list must raise, not corrupt the
    pool (a double-freed page would be handed to two sequences at once)."""
    a = PageAllocator(8)
    pages = a.alloc(3)
    a.free(pages)
    with pytest.raises(ValueError):
        a.free([pages[0]])
    # a rejected batch must leave the pool untouched (validate-then-mutate)
    live = a.alloc(1)
    with pytest.raises(ValueError):
        a.free(live + [pages[1]])  # second id is free -> whole call rejected
    assert a.refcount(live[0]) == 1  # the live page kept its reference
    a.free(live)
    assert a.free_count == 7
    # freeing the same id twice IN ONE CALL needs refcount >= 2
    p = a.alloc(1)
    with pytest.raises(ValueError):
        a.free([p[0], p[0]])
    a.incref(p)
    a.free([p[0], p[0]])  # ref 2 -> 0: legal
    assert a.free_count == 7


def test_allocator_refcount_sharing():
    """incref'd pages return to the free list only at refcount zero, and
    refcount-0 pages can never gain holders."""
    a = PageAllocator(8)
    pages = a.alloc(2)
    a.incref(pages)
    assert [a.refcount(p) for p in pages] == [2, 2]
    a.free(pages)  # one holder left
    assert a.free_count == 5
    a.free(pages)  # last holder: pages return
    assert a.free_count == 7
    assert all(a.refcount(p) == 0 for p in pages)
    with pytest.raises(ValueError):
        a.incref([pages[0]])  # free page cannot gain a holder


def test_cache_admission_math():
    cfg = ModelConfig(vocab_size=64, dim=32, n_layers=1, n_heads=4, n_kv_heads=2,
                      hidden_dim=64, max_seq_len=256, dtype="float32")
    c = PagedKVCache(cfg, num_pages=8, page_size=16, max_pages_per_slot=4)
    assert c.pages_needed(1) == 1
    assert c.pages_needed(16) == 1
    assert c.pages_needed(17) == 2
    assert c.can_admit(7 * 16)  # 8 pages minus the reserved null page
    assert not c.can_admit(7 * 16 + 1)
    seq = c.open_sequence(40)  # 3 pages
    assert len(seq.pages) == 3
    c.grow(seq, 60)  # 4 pages
    assert len(seq.pages) == 4
    with pytest.raises(OutOfPages):
        c.grow(seq, 100)  # exceeds max_pages_per_slot
    c.close_sequence(seq)
    assert c.allocator.free_count == 7


def test_ragged_kernel_matches_xla_fallback():
    key = jax.random.PRNGKey(0)
    B, H, K, hd, P, ps, W = 2, 4, 2, 128, 12, 32, 5
    q = jax.random.normal(key, (B, H, hd), jnp.float32)
    kp = jax.random.normal(jax.random.fold_in(key, 1), (P, K, ps, hd), jnp.float32)
    vp = jax.random.normal(jax.random.fold_in(key, 2), (P, K, ps, hd), jnp.float32)
    pt = jnp.asarray(np.random.default_rng(0).permutation(P)[: B * W].reshape(B, W))
    kv_lens = jnp.array([150, 33])
    ref = paged_decode_xla(q, kp, vp, pt, kv_lens)
    out = paged_decode_pallas(q, kp, vp, pt, kv_lens, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


def test_page_recycling_does_not_corrupt():
    """Two batches through the same engine must reuse freed pages without
    leaking state: greedy output for an identical request must be identical
    before and after the pool has been heavily recycled."""
    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                     hidden_dim=128, max_seq_len=256, dtype="float32")
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=8, max_batch_slots=2, page_size=32,
                                 num_pages=16, seed=0), mc)
    probe = GenerationRequest(prompt="canonical probe text", temperature=0.0,
                              max_new_tokens=8)
    before = eng.generate_batch([probe])[0].text
    # churn the pool with other requests
    churn = [GenerationRequest(prompt=f"churn {i} " * (3 + i), request_id=i,
                               temperature=0.9, max_new_tokens=8) for i in range(7)]
    eng.generate_batch(churn)
    after = eng.generate_batch([probe])[0].text
    assert before == after
    # all pages returned except those the prefix cache retains (each held at
    # exactly one reference — the cache's own)
    sched = eng._scheduler
    cached = sched._prefix_cache.cached_pages if sched._prefix_cache else 0
    assert (sched.cache.allocator.free_count
            == sched.cache.num_pages - 1 - cached)  # -1: null page


def test_backpressure_small_pool():
    """A pool that fits only one sequence at a time must still complete all
    requests (admission waits for pages instead of failing)."""
    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                     hidden_dim=128, max_seq_len=256, dtype="float32")
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=8, max_batch_slots=4, page_size=32,
                                 num_pages=0,  # floor: B * max_pages_per_slot
                                 seed=0), mc)
    # shrink the pool artificially to 1 slot's worth
    sched = eng._scheduler
    reqs = [GenerationRequest(prompt="p" * 40, request_id=i, temperature=0.4,
                              max_new_tokens=8) for i in range(5)]
    out = eng.generate_batch(reqs)
    assert [r.request_id for r in out] == list(range(5))
    assert all(r.error is None for r in out)


def test_pool_floor_makes_every_request_admittable():
    """Admission has no fail-fast branch by design (ADVICE r2: it was
    unreachable): the constructor floors the pool at one full-length
    sequence + the null page, prompts truncate at submit, and decode trims
    at max_len — so even a worst-case request admits and completes.  This
    test pins the INVARIANT that removal rests on."""
    mc = ModelConfig(vocab_size=512, dim=64, n_layers=1, n_heads=4, n_kv_heads=2,
                     hidden_dim=128, max_seq_len=8192, dtype="float32")
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=8, max_batch_slots=1, page_size=128,
                                 num_pages=2, seed=0), mc)
    sched = eng._scheduler
    # num_pages=2 asked for a 2-page budget; the floor must win
    assert sched.cache.num_pages >= sched.cache.max_pages_per_slot + 1
    big = GenerationRequest(prompt="x" * 7000, request_id=0, temperature=0.0,
                            max_new_tokens=8)
    small = GenerationRequest(prompt="ok", request_id=1, temperature=0.0,
                              max_new_tokens=4)
    out = eng.generate_batch([big, small])
    assert out[0].error is None and out[0].completion_tokens <= 8
    assert out[1].error is None
