"""Tree speculative decoding (ISSUE 19): on-device tree drafting,
ancestor-mask verify on the ragged-span family, adaptive depth.

Three layers of contract:

* ops-level — ``draft_tree_lookup`` proposes the ``width`` most recent
  n-gram continuations (root-deduped, depth-clamped); ``verify_tree``
  preserves the root marginal exactly under sequential multi-candidate
  rejection and degenerates to the longest argmax path on greedy rows;
  the ancestor-bitmask generalization of ``ragged_spans_xla`` scores
  every branch identically to per-branch LINEAR dispatches of the same
  tokens (the mask is the only thing that changes).

* scheduler-level — greedy outputs token-identical across no-spec /
  linear (``LMRS_SPEC_TREE=0``) / tree over the prefix-cache x int8-KV
  matrix; the kill switch keeps every tree counter at zero; the adaptive
  ramp deepens on accept streaks, collapses to off on rejection streaks,
  and re-probes periodically; draft hints are advisory (outputs
  byte-identical with and without).

* surface — the windowed ``spec_tree`` report block on the jax
  scheduler, and the mock's deterministic emulation of the same block
  (including the draft-hint acceptance bump deviceless CI asserts on).
"""

from __future__ import annotations

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.ops.paged_attention import pack_spans, ragged_spans_xla
from lmrs_tpu.ops.speculative import draft_tree_lookup, verify_tree

# --------------------------------------------------------------- ops level


def test_draft_tree_lookup_most_recent_first():
    # query bigram (5,6) recurs at 0 (-> 7), 4 (-> 8), 8 (-> 9); the
    # query occurrence itself (pos 12) is excluded.  width=2 keeps the
    # two most recent, most recent first.
    hist = [5, 6, 7, 1, 5, 6, 8, 2, 5, 6, 9, 3, 5, 6]
    buf = jnp.asarray([hist + [0] * 2])
    chains, nv = draft_tree_lookup(buf, jnp.asarray([len(hist)]), k=2,
                                   width=2)
    assert nv[0].tolist() == [2, 2]
    assert chains[0, 0].tolist() == [9, 3]   # pos 8, most recent
    assert chains[0, 1].tolist() == [8, 2]   # pos 4


def test_draft_tree_lookup_dedups_duplicate_roots():
    # both earlier (5,6) occurrences continue with 7 — a duplicate root
    # candidate has zero residual mass under sequential rejection, so
    # the older chain is dropped (n_valid 0)
    hist = [5, 6, 7, 5, 6, 7, 5, 6]
    buf = jnp.asarray([hist + [0] * 2])
    chains, nv = draft_tree_lookup(buf, jnp.asarray([len(hist)]), k=2,
                                   width=2)
    assert int(nv[0, 0]) > 0
    assert int(chains[0, 0, 0]) == 7
    assert int(nv[0, 1]) == 0


def test_draft_tree_lookup_depth_clamp():
    hist = [5, 6, 7, 1, 5, 6, 8, 2, 5, 6]
    buf = jnp.asarray([hist + [0] * 3])
    _, nv_full = draft_tree_lookup(buf, jnp.asarray([len(hist)]), k=3,
                                   width=2)
    _, nv_one = draft_tree_lookup(buf, jnp.asarray([len(hist)]), k=3,
                                  width=2, depth=jnp.asarray([1]))
    assert int(nv_full.max()) > 1
    assert int(nv_one.max()) == 1
    _, nv_off = draft_tree_lookup(buf, jnp.asarray([len(hist)]), k=3,
                                  width=2, depth=jnp.asarray([0]))
    assert int(nv_off.max()) == 0


def test_verify_tree_greedy_picks_matching_chain():
    """One-hot (greedy) node distributions: the chain whose first token
    is the root argmax wins, its matching prefix is accepted, and the
    bonus comes from the last accepted node."""
    v, W, k = 8, 2, 2
    probs = np.zeros((1, 1 + W * k, v), np.float32)
    probs[0, 0, 4] = 1.0          # root wants 4
    probs[0, 3, 5] = 1.0          # after chain-1 token 0 (slot 1+k): 5
    probs[0, 4, 6] = 1.0          # after chain-1 token 1: bonus 6
    probs[0, 1, 7] = 1.0          # chain-0 nodes (never reached)
    probs[0, 2, 7] = 1.0
    chains = jnp.asarray([[[3, 9 % v], [4, 5]]], jnp.int32)
    nv = jnp.asarray([[2, 2]], jnp.int32)
    emit, count, chain, depth = verify_tree(
        jnp.asarray(probs), chains, nv, jax.random.PRNGKey(0))
    assert int(chain[0]) == 1
    assert int(depth[0]) == 2
    assert int(count[0]) == 3
    assert emit[0, :3].tolist() == [4, 5, 6]


def test_verify_tree_greedy_rejects_all_when_no_chain_matches():
    v, W, k = 8, 2, 2
    probs = np.zeros((1, 1 + W * k, v), np.float32)
    probs[0, :, 2] = 1.0          # root argmax 2, no candidate proposes it
    chains = jnp.asarray([[[3, 3], [4, 4]]], jnp.int32)
    nv = jnp.asarray([[2, 2]], jnp.int32)
    emit, count, chain, depth = verify_tree(
        jnp.asarray(probs), chains, nv, jax.random.PRNGKey(1))
    assert int(chain[0]) == -1
    assert int(depth[0]) == 0
    assert int(count[0]) == 1
    assert int(emit[0, 0]) == 2   # the root argmax still comes out


def test_verify_tree_preserves_root_marginal():
    """The first emitted token's marginal must equal the root
    distribution exactly — the SpecInfer sequential-rejection guarantee,
    candidate-set-independent."""
    v, W, k = 4, 2, 1
    rng = np.random.default_rng(0)
    node = rng.dirichlet(np.ones(v), size=1 + W * k).astype(np.float32)
    probs = jnp.asarray(node[None])           # [1, 3, V]
    chains = jnp.asarray([[[2], [3]]], jnp.int32)
    nv = jnp.asarray([[1, 1]], jnp.int32)

    n = 4000
    emit, _, _, _ = jax.vmap(
        lambda key: verify_tree(probs, chains, nv, key)
    )(jax.random.split(jax.random.PRNGKey(7), n))
    first = np.asarray(emit[:, 0, 0])
    freq = np.bincount(first, minlength=v) / n
    np.testing.assert_allclose(freq, node[0], atol=0.03)


def test_verify_tree_count_bounds():
    v, W, k = 8, 3, 3
    rng = np.random.default_rng(2)
    probs = jnp.asarray(
        rng.dirichlet(np.ones(v), size=(2, 1 + W * k)).astype(np.float32))
    chains = jnp.asarray(rng.integers(0, v, (2, W, k)), jnp.int32)
    nv = jnp.asarray([[3, 2, 0], [0, 0, 0]], jnp.int32)
    emit, count, chain, depth = verify_tree(probs, chains, nv,
                                            jax.random.PRNGKey(4))
    c = np.asarray(count)
    d = np.asarray(depth)
    assert ((1 <= c) & (c <= k + 1)).all()
    assert (d == c - 1).all()
    assert int(chain[1]) == -1 and int(count[1]) == 1  # all-invalid row


def _anc_fixture(seed, q_lens, h=4, kh=2, hd=16, ps=16, n_pages=16,
                 width=2):
    b = len(q_lens)
    qs, total = pack_spans(np.asarray(q_lens, np.int32))
    rng = jax.random.split(jax.random.PRNGKey(seed), 5)
    qf = jax.random.normal(rng[0], (total, h, hd), jnp.float32)
    knf = jax.random.normal(rng[1], (total, kh, hd), jnp.float32)
    vnf = jax.random.normal(rng[2], (total, kh, hd), jnp.float32)
    kp = jax.random.normal(rng[3], (n_pages, kh, ps, hd), jnp.float32)
    vp = jax.random.normal(rng[4], (n_pages, kh, ps, hd), jnp.float32)
    tables = jnp.asarray(
        np.random.default_rng(seed).permutation(n_pages - 1)[: b * width]
        .reshape(b, width) + 1, jnp.int32)
    row_flat = np.full((total,), b, np.int32)
    for i, (s, l) in enumerate(zip(qs, q_lens)):
        row_flat[s:s + l] = i
    return qs, total, qf, knf, vnf, kp, vp, tables, row_flat


def test_ancestor_mask_matches_per_branch_linear_dispatch():
    """The tree span [cur, chain0 (k), chain1 (k)] under ancestor
    bitmasks must produce, for every node, EXACTLY the attention output
    a linear span [cur, chain_c] produces for that node on fresh pools —
    column layout differs (chain-1 lands at healed columns) but the
    visible key/value SET is identical, and that is all attention sees."""
    k, W = 2, 2
    base = 21
    q_lens = [1 + W * k]
    qs, total, qf, knf, vnf, kp, vp, tables, row_flat = _anc_fixture(
        5, q_lens)
    s0 = qs[0]

    # host-built ancestor masks: cur keeps the linear sentinel (0);
    # chain c node j sees {cur} + its own chain prefix through itself
    anc = np.zeros((total,), np.uint32)
    for c in range(W):
        bits = 1  # bit 0 = cur
        for j in range(k):
            o = 1 + c * k + j
            bits |= np.uint32(1) << np.uint32(o)
            anc[s0 + o] = bits
    got, _, _ = ragged_spans_xla(
        qf, knf, vnf, kp, vp, tables, jnp.asarray([base], jnp.int32),
        jnp.asarray(qs), jnp.asarray(q_lens, jnp.int32),
        jnp.asarray(row_flat), anc_masks=jnp.asarray(anc.view(np.int32)))

    for c in range(W):
        # linear reference: [cur, chain_c] as a plain causal span over
        # fresh pools; flat tokens re-packed into the reference layout
        lin_lens = [1 + k]
        lqs, ltotal = pack_spans(np.asarray(lin_lens, np.int32))
        sel = [s0] + [s0 + 1 + c * k + j for j in range(k)]
        pad = ltotal - len(sel)

        def lay(x):
            picked = jnp.stack([x[i] for i in sel])
            return jnp.concatenate(
                [picked, jnp.zeros((pad,) + x.shape[1:], x.dtype)])

        lrow = np.full((ltotal,), 1, np.int32)
        lrow[lqs[0]:lqs[0] + lin_lens[0]] = 0
        want, _, _ = ragged_spans_xla(
            lay(qf), lay(knf), lay(vnf), kp, vp, tables,
            jnp.asarray([base], jnp.int32), jnp.asarray(lqs),
            jnp.asarray(lin_lens, jnp.int32), jnp.asarray(lrow))
        # cur's output must agree (it sees only committed context + self
        # in both layouts), and every chain-c node must agree
        np.testing.assert_allclose(np.asarray(got[s0]),
                                   np.asarray(want[lqs[0]]),
                                   rtol=2e-5, atol=2e-5)
        for j in range(k):
            np.testing.assert_allclose(
                np.asarray(got[s0 + 1 + c * k + j]),
                np.asarray(want[lqs[0] + 1 + j]),
                rtol=2e-5, atol=2e-5)


def test_ancestor_mask_zero_rows_keep_linear_rule():
    """A dispatch mixing an all-zero-mask span with a tree span must
    score the zero-mask span exactly as the no-mask call does (the
    sentinel keeps linear spans byte-identical)."""
    q_lens = [3, 5]
    qs, total, qf, knf, vnf, kp, vp, tables, row_flat = _anc_fixture(
        6, q_lens)
    bases = jnp.asarray([10, 4], jnp.int32)
    anc = np.zeros((total,), np.uint32)
    s1 = qs[1]  # row 1 becomes a [cur, chain0(2), chain1(2)] tree span
    for c in range(2):
        bits = 1
        for j in range(2):
            o = 1 + c * 2 + j
            bits |= np.uint32(1) << np.uint32(o)
            anc[s1 + o] = bits
    got, _, _ = ragged_spans_xla(
        qf, knf, vnf, kp, vp, tables, bases, jnp.asarray(qs),
        jnp.asarray(q_lens, jnp.int32), jnp.asarray(row_flat),
        anc_masks=jnp.asarray(anc.view(np.int32)))
    want, _, _ = ragged_spans_xla(
        qf, knf, vnf, kp, vp, tables, bases, jnp.asarray(qs),
        jnp.asarray(q_lens, jnp.int32), jnp.asarray(row_flat))
    s0 = qs[0]
    np.testing.assert_allclose(np.asarray(got[s0:s0 + 3]),
                               np.asarray(want[s0:s0 + 3]),
                               rtol=2e-6, atol=2e-6)


# --------------------------------------------------------- scheduler level


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


def _cfg(**kw) -> EngineConfig:
    base = dict(backend="jax", scheduler="continuous", max_tokens=20,
                max_batch_slots=2, seed=0, decode_block=3,
                prefill_chunk=64, mixed_batch=True)
    base.update(kw)
    return EngineConfig(**base)


def _requests(n: int = 4) -> list[GenerationRequest]:
    # repetitive bodies: the n-gram draft actually fires
    reqs = []
    for i in range(n):
        body = f"request {i} " + "the cat sat on the mat " * (2 + i % 2)
        reqs.append(GenerationRequest(prompt=body, request_id=i,
                                      temperature=0.0,
                                      max_new_tokens=12 + i))
    return reqs


def _run(cfg: EngineConfig, mc, reqs):
    eng = JaxEngine(cfg, mc)
    out = eng.generate_batch(reqs)
    sched = eng._scheduler
    assert sched.audit() == []
    assert all(r.error is None for r in out)
    texts = [(r.text, r.finish_reason, r.completion_tokens) for r in out]
    m = dict(sched.metrics)
    rep = sched._spec_tree_report()
    eng.shutdown()
    return texts, m, rep


@pytest.mark.parametrize("prefix_cache,kv_q", [(True, None),
                                               (False, "int8")])
def test_spec_tree_greedy_identity_matrix(monkeypatch, prefix_cache, kv_q):
    """The ISSUE 19 acceptance bar: greedy outputs token-identical
    across no-spec / linear spec (LMRS_SPEC_TREE=0) / tree spec, with
    mixed batches armed, over prefix-cache and int8-KV compositions —
    and the tree arm must actually dispatch tree spans while the linear
    arm keeps every tree counter at zero (the kill-switch contract)."""
    mc = tiny_model()
    reqs = _requests()
    kw = dict(prefix_cache=prefix_cache)
    if kv_q:
        kw.update(page_size=32, kv_quantize=kv_q)
    want, _, _ = _run(_cfg(speculate_k=0, **kw), mc, reqs)
    monkeypatch.setenv("LMRS_SPEC_TREE", "0")
    lin, m_lin, rep_lin = _run(_cfg(speculate_k=3, **kw), mc, reqs)
    assert m_lin["spec_tree_dispatches"] == 0
    assert rep_lin["enabled"] is False
    monkeypatch.setenv("LMRS_SPEC_TREE", "1")
    tree, m_tree, rep_tree = _run(_cfg(speculate_k=3, **kw), mc, reqs)
    assert m_tree["spec_tree_dispatches"] > 0, "tree path not exercised"
    assert rep_tree["enabled"] is True
    assert lin == want
    assert tree == want


def test_spec_tree_fuzzed_admission_audit_clean(monkeypatch):
    """Varied lengths / budgets / temperatures through the tree path on
    small slot counts (admission churn, preemption pressure): every
    invariant audit stays clean and every request terminates in budget."""
    monkeypatch.setenv("LMRS_SPEC_TREE", "1")
    rng = np.random.default_rng(11)
    words = ["alpha", "beta", "gamma", "delta", "the", "cat", "sat"]
    reqs = []
    for i in range(7):
        body = " ".join(rng.choice(words, 8 + 10 * (i % 3)).tolist())
        reqs.append(GenerationRequest(
            prompt=(body + " ") * (1 + i % 2), request_id=i,
            temperature=float(rng.choice([0.0, 0.8])),
            top_k=int(rng.choice([0, 40])),
            max_new_tokens=int(rng.integers(4, 18))))
    eng = JaxEngine(_cfg(speculate_k=3, max_batch_slots=3), tiny_model())
    out = eng.generate_batch(reqs)
    sched = eng._scheduler
    assert sched.audit() == []
    assert sched.metrics["spec_tree_dispatches"] > 0
    eng.shutdown()
    for i, r in enumerate(out):
        assert r.error is None
        assert 0 < r.completion_tokens <= reqs[i].max_new_tokens


def test_spec_ramp_adaptive_up_down_and_probe():
    eng = JaxEngine(_cfg(speculate_k=4), tiny_model())
    sched = eng._scheduler
    st = SimpleNamespace(spec_ema=0.9, spec_probe=0)
    assert sched._spec_ramp(st, 2) == 3           # accept streak: deepen
    assert sched._spec_ramp(st, 4) == 4           # capped at k
    st.spec_ema = 0.3
    assert sched._spec_ramp(st, 3) == 2           # soft collapse: shallower
    assert sched._spec_ramp(st, 1) == 1           # floored at 1
    st.spec_ema = 0.1
    assert sched._spec_ramp(st, 2) == 0           # hard collapse: off
    # off rows re-probe at half depth every 8 idle steps, EMA reset
    st = SimpleNamespace(spec_ema=0.05, spec_probe=0)
    depths = [sched._spec_ramp(st, 0) for _ in range(8)]
    assert depths[:7] == [0] * 7
    assert depths[7] == max(1, sched.spec_k // 2)
    assert st.spec_ema == 0.5 and st.spec_probe == 0
    eng.shutdown()


def test_draft_hint_is_advisory_for_greedy_outputs(monkeypatch):
    """A draft hint may only change WHERE tokens come from, never which
    tokens come out: greedy outputs byte-identical with and without."""
    monkeypatch.setenv("LMRS_SPEC_TREE", "1")
    mc = tiny_model()
    plain = _requests(3)
    want, _, _ = _run(_cfg(speculate_k=3), mc, plain)
    hinted = _requests(3)
    for r in hinted:
        r.draft_hint = "the cat sat on the mat the cat sat on the mat"
    got, m, _ = _run(_cfg(speculate_k=3), mc, hinted)
    assert m["spec_tree_dispatches"] > 0
    assert got == want


def test_spec_tree_report_block_shape():
    eng = JaxEngine(_cfg(speculate_k=3), tiny_model())
    eng.generate_batch(_requests(3))
    sched = eng._scheduler
    m = sched.metrics
    blk = sched.metrics_report()["spec_tree"]
    assert blk["enabled"] is True
    assert blk["dispatches"] == m["spec_tree_dispatches"] > 0
    assert blk["width"] >= 1 and isinstance(blk["adaptive"], bool)
    rows = m["spec_tree_rows"]
    assert blk["mean_accept_depth"] == pytest.approx(
        m["spec_accept_depth_sum"] / rows if rows else 0.0, abs=1e-3)
    assert blk["accept_per_step"] == pytest.approx(
        m["spec_accepted_tokens"] / rows if rows else 0.0, abs=1e-3)
    eng.shutdown()
    off = JaxEngine(_cfg(speculate_k=0), tiny_model())
    off.generate_batch(_requests(2))
    assert "spec_tree" not in off._scheduler.metrics_report()
    off.shutdown()


# ---------------------------------------------------------------- mock arm


def test_mock_engine_spec_tree_block(monkeypatch):
    """No-device parity: same gate composition, same block keys, and the
    deterministic hint bump (full-depth acceptance on hinted requests)
    that the live cross-refresh CI leans on."""
    from lmrs_tpu.engine.mock import MockEngine

    reqs = [GenerationRequest(prompt="alpha beta gamma " * 20,
                              request_id=i) for i in range(3)]
    eng = MockEngine(speculate_k=4)
    assert eng.spec_tree
    eng.generate_batch(reqs)
    blk = eng.engine_metrics()["spec_tree"]
    assert blk["enabled"] and blk["dispatches"] > 0
    assert blk["accept_per_step"] == pytest.approx(2.0)  # k//2, unhinted
    assert eng.draft_hints == []

    hinted = [GenerationRequest(prompt="alpha beta gamma " * 20,
                                request_id=10 + i,
                                draft_hint="prior summary text")
              for i in range(2)]
    eng2 = MockEngine(speculate_k=4)
    eng2.generate_batch(hinted)
    blk2 = eng2.engine_metrics()["spec_tree"]
    assert blk2["accept_per_step"] == pytest.approx(4.0)  # full depth
    assert eng2.draft_hints == ["prior summary text"] * 2

    monkeypatch.setenv("LMRS_SPEC_TREE", "0")
    off = MockEngine(speculate_k=4)
    assert not off.spec_tree
    off.generate_batch(reqs)
    assert "spec_tree" not in off.engine_metrics()
