"""Offline quality gate (VERDICT r1 item 4): the stack must demonstrably
SUMMARIZE, not just stream tokens.

Two layers, both scored with the in-tree ROUGE harness against stored /
ground-truth baselines:

1. ``test_parity_vs_committed_baseline`` — the full pipeline on the real
   7.4 h example transcript scored against the committed curated baseline
   (examples/baseline_summary.json).  The mock engine is extractive, so
   the absolute score is modest; the gate is a calibrated regression
   tripwire (measured 0.042 ROUGE-L / 0.084 ROUGE-1 on 2026-07-30 — a
   format or content collapse drops it to ~0).
2. ``test_trained_model_beats_extractive_baseline`` — the REAL gate: a
   model is fine-tuned through the production training stack on synthetic
   transcript→summary pairs (eval/synthetic.py), held-out prompts are
   decoded through the production continuous-batching engine, and the
   mean ROUGE-L against ground truth must clear a non-trivial threshold
   AND beat the trivial lead-1 extractive baseline by a wide margin.
   Calibration (2026-07-30, CPU, fixed seeds): model 0.396, extractive
   0.048 — gates set at 0.30 and 3x.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

BASELINE_FIXTURE = (Path(__file__).parent.parent / "examples"
                    / "baseline_summary.json")


def test_parity_vs_committed_baseline(example_transcript):
    from lmrs_tpu.config import EngineConfig, PipelineConfig
    from lmrs_tpu.eval.parity import load_baseline, run_parity

    baseline = load_baseline(BASELINE_FIXTURE)
    assert len(baseline.split()) > 150  # a real summary, not a stub
    cfg = PipelineConfig(engine=EngineConfig(backend="mock"))
    report = run_parity(example_transcript, baseline, cfg, threshold=0.02)
    assert report.passed, report.to_dict()
    assert report.rouge1_f >= 0.04, report.to_dict()


@pytest.fixture(scope="module")
def trained_summarizer():
    """Fine-tune the tiny byte-level model on synthetic pairs through the
    production path: JSONL -> training.cli.load_examples (loss masked to
    the summary) -> make_train_step."""
    import jax
    import jax.numpy as jnp
    import optax

    from lmrs_tpu.config import ModelConfig
    from lmrs_tpu.data.tokenizer import ByteTokenizer
    from lmrs_tpu.eval.synthetic import make_dataset
    from lmrs_tpu.models.transformer import init_params
    from lmrs_tpu.training.cli import batches, load_examples
    from lmrs_tpu.training.train import make_train_step

    cfg = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                      dtype="float32")
    tok = ByteTokenizer()
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as td:
        data_path = Path(td) / "train.jsonl"
        data_path.write_text("\n".join(
            json.dumps({"prompt": ex["prompt"], "summary": ex["summary"]})
            for ex in make_dataset(192, seed=0)))
        seqs, masks = load_examples(str(data_path), tok)

    params = init_params(cfg, jax.random.PRNGKey(0))
    optimizer = optax.adamw(4e-3)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(cfg, optimizer, None, masked=True)
    it = batches(seqs, masks, 16, 320, 0)
    loss = None
    for _ in range(200):
        t, m = next(it)
        params, opt_state, loss = step_fn(params, opt_state,
                                          jnp.asarray(t), jnp.asarray(m))
    assert float(loss) < 0.5, f"training failed to converge: loss {float(loss)}"
    return cfg, tok, params


def test_trained_model_beats_extractive_baseline(trained_summarizer):
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine
    from lmrs_tpu.eval.rouge import rouge_l
    from lmrs_tpu.eval.synthetic import extractive_baseline, make_dataset

    cfg, tok, params = trained_summarizer
    engine = JaxEngine(
        EngineConfig(backend="jax", scheduler="continuous", max_tokens=48,
                     max_batch_slots=4, seed=0, decode_block=8),
        cfg, params=params, tokenizer=tok)
    # Seed-disjoint is not prompt-disjoint (ADVICE r2): with 12 topics and
    # 2-3 draws per example, a held-out prompt can collide verbatim with a
    # training prompt.  Filter exact-prompt overlap so the gate measures
    # generalization, drawing extra candidates to keep the set at 8.
    train_prompts = {ex["prompt"] for ex in make_dataset(192, seed=0)}
    held = [ex for ex in make_dataset(32, seed=999)
            if ex["prompt"] not in train_prompts][:8]
    assert len(held) == 8, "synthetic generator collided on all candidates"
    reqs = [GenerationRequest(prompt=ex["prompt"], request_id=i,
                              temperature=0.0, max_new_tokens=48)
            for i, ex in enumerate(held)]
    outs = engine.generate_batch(reqs)
    engine.shutdown()

    model_f = [rouge_l(o.text, ex["summary"])["f"]
               for ex, o in zip(held, outs)]
    extract_f = [rouge_l(extractive_baseline(ex["prompt"]), ex["summary"])["f"]
                 for ex in held]
    mean_model = float(np.mean(model_f))
    mean_extract = float(np.mean(extract_f))
    # non-trivial absolute gate + wide margin over the trivial baseline
    assert mean_model >= 0.30, (mean_model, model_f)
    assert mean_model > 3 * mean_extract, (mean_model, mean_extract)


def test_trained_model_quality_survives_kv_int8(trained_summarizer):
    """The REAL numerics gate for kv_quantize=int8 (per-slot/head/channel
    scales, ops/quant.py KV section): the fine-tuned model decoded through
    int8 KV pages must keep its learned-summarization quality, not merely
    not crash.  A scale-wiring bug (wrong rows, wrong channel axis) floors
    ROUGE-L to extractive-baseline territory instantly."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine
    from lmrs_tpu.eval.rouge import rouge_l
    from lmrs_tpu.eval.synthetic import make_dataset

    cfg, tok, params = trained_summarizer
    engine = JaxEngine(
        EngineConfig(backend="jax", scheduler="continuous", max_tokens=48,
                     max_batch_slots=4, seed=0, decode_block=8,
                     page_size=32, kv_quantize="int8"),
        cfg, params=params, tokenizer=tok)
    train_prompts = {ex["prompt"] for ex in make_dataset(192, seed=0)}
    held = [ex for ex in make_dataset(32, seed=999)
            if ex["prompt"] not in train_prompts][:8]
    reqs = [GenerationRequest(prompt=ex["prompt"], request_id=i,
                              temperature=0.0, max_new_tokens=48)
            for i, ex in enumerate(held)]
    outs = engine.generate_batch(reqs)
    engine.shutdown()
    model_f = [rouge_l(o.text, ex["summary"])["f"]
               for ex, o in zip(held, outs)]
    mean_model = float(np.mean(model_f))
    # same absolute gate as the full-precision test: int8 KV must not cost
    # the learned behavior (small per-example wobble is expected)
    assert mean_model >= 0.28, (mean_model, model_f)


# ---------------------------------------------------- CLI end-to-end gate


MAP_TEMPLATE = "List the topics.\n{transcript}\nTopics:"
REDUCE_TEMPLATE = "List the topics.\n{summaries}\nTopics:"
CLI_CHUNK_TOKENS = 384  # forces multi-chunk map on the held-out transcript

# Condensed video-editor reduce template: the SAME instruction-following
# contract as prompts/assets/video_editor_reduce.txt (the reference's core
# reduce contract, result_aggregator.py:146-175 — five exact ### headers,
# [H:MM:SS] timestamps carried through, triggered by the literal
# "TIMELINE SUMMARY"), condensed to fit quality-tiny's 1024-byte window
# alongside the tagged summaries (the full ~1.2 KB asset would force a
# 2048 window and multiply the suite's CPU compile cost).
VIDEO_SECTIONS = ("TIMELINE SUMMARY", "KEY MOMENTS", "TOPIC SECTIONS",
                  "POTENTIAL B-ROLL", "QUOTE TIMESTAMPS")
VIDEO_REDUCE_TEMPLATE = (
    "Merge the edit notes. Keep every timestamp.\n{summaries}\n"
    "Reply with exactly these sections:\n"
    + "\n".join(f"### {s}" for s in VIDEO_SECTIONS) + "\n")


def _make_cli_transcript(rng):
    """A transcript in the CLI input schema (reference README.md:162-175)
    whose ground-truth summary is its topic list in order of appearance."""
    from lmrs_tpu.eval.synthetic import _FILLER, _OPENERS, TOPICS

    n_topics = int(rng.integers(3, 6))
    topics = [TOPICS[i] for i in rng.choice(len(TOPICS), n_topics,
                                            replace=False)]
    segs, t = [], 0.0
    for topic in topics:
        if rng.random() < 0.6:
            segs.append({"start": t, "end": t + 4.0, "speaker": "SPEAKER_00",
                         "text": str(rng.choice(_FILLER))})
            t += float(rng.integers(20, 50))
        opener = str(rng.choice(_OPENERS)).format(t=topic)
        segs.append({"start": t, "end": t + 4.0, "speaker": "SPEAKER_00",
                     "text": opener + "."})
        t += float(rng.integers(20, 50))
    return {"segments": segs}, topics


def _product_format_pairs(transcript, topics):
    """(prompt, summary) pairs in the EXACT formats the CLI will produce:
    map prompts through the real preprocessor + chunker (context header
    included), the reduce prompt through the real aggregator formatter."""
    from types import SimpleNamespace

    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.data.chunker import TranscriptChunker
    from lmrs_tpu.data.preprocessor import format_timestamp, preprocess_transcript
    from lmrs_tpu.data.tokenizer import ByteTokenizer
    from lmrs_tpu.prompts import safe_format
    from lmrs_tpu.reduce.aggregator import ResultAggregator

    chunker = TranscriptChunker(max_tokens_per_chunk=CLI_CHUNK_TOKENS,
                                overlap_tokens=0, context_tokens=150,
                                tokenizer=ByteTokenizer())
    agg = ResultAggregator(SimpleNamespace(config=EngineConfig()),
                           tokenizer=ByteTokenizer())
    processed = preprocess_transcript(transcript["segments"])
    chunks = chunker.chunk_transcript(processed)
    pairs, tagged = [], []
    for c in chunks:
        in_chunk = sorted((t for t in topics if t in c.text),
                          key=c.text.find)
        target = " " + ", ".join(in_chunk) + "." if in_chunk else " none."
        pairs.append({
            "prompt": safe_format(MAP_TEMPLATE,
                                  transcript=c.text_with_context),
            "summary": target,
        })
        tagged.append(
            f"[Time: {format_timestamp(c.start_time)} - "
            f"{format_timestamp(c.end_time)}]\n{target}")
    red = agg._build_request(tagged, REDUCE_TEMPLATE, metadata=None)
    pairs.append({"prompt": red.prompt,
                  "summary": " " + ", ".join(topics) + "."})
    return pairs


def _video_reduce_items(rng):
    """(start_s, topic) beats for one synthetic recording, times past one
    hour so format_timestamp emits the H:MM:SS form the contract names.
    Minute-aligned: the stamps stay arbitrary per example (the model must
    COPY them, not memorize them), but 3-4 varying digits per stamp keep
    byte-level copy induction learnable inside the suite's training
    budget — full second-resolution stamps (6 varying digits) measured
    0/6 exact carry-through at the same budget (digit spans resist
    copy-induction; the r4 speculation study hit the same wall)."""
    from lmrs_tpu.eval.synthetic import TOPICS

    n = int(rng.integers(2, 4))
    topics = [TOPICS[i] for i in rng.choice(len(TOPICS), n, replace=False)]
    t = 3600.0 + 60.0 * float(rng.integers(0, 30))
    items = []
    for topic in topics:
        items.append((t, topic))
        t += 60.0 * float(rng.integers(1, 8))
    return items


def _video_reduce_pair(items):
    """(prompt, target) in the EXACT product reduce format: chunk summaries
    carrying inline [H:MM:SS] markers, time-tagged and block-formatted by
    the real aggregator, with a five-section target document that copies
    every timestamp through (the reference's carry-every-timestamp
    contract)."""
    from types import SimpleNamespace

    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.data.preprocessor import format_timestamp
    from lmrs_tpu.data.tokenizer import ByteTokenizer
    from lmrs_tpu.reduce.aggregator import ResultAggregator

    agg = ResultAggregator(SimpleNamespace(config=EngineConfig()),
                           tokenizer=ByteTokenizer())
    tagged = []
    for start, topic in items:
        ts = format_timestamp(start)
        tagged.append(f"[Time: {ts} - {format_timestamp(start + 40)}]\n"
                      f"[{ts}] {topic}")
    prompt = agg._build_request(tagged, VIDEO_REDUCE_TEMPLATE,
                                metadata=None).prompt
    stamps = [format_timestamp(s) for s, _ in items]
    beats = "\n".join(f"[{ts}] {topic}" for (_, topic), ts
                      in zip(items, stamps))
    target = (
        f" ### TIMELINE SUMMARY\n{beats}\n"
        f"### KEY MOMENTS\n[{stamps[0]}] {items[0][1]}\n"
        f"### TOPIC SECTIONS\n[{stamps[0]}]-[{stamps[-1]}] "
        + ", ".join(t for _, t in items) + "\n"
        f"### POTENTIAL B-ROLL\n[{stamps[-1]}] {items[-1][1]}\n"
        f"### QUOTE TIMESTAMPS\n[{stamps[0]}] {items[0][1]}\n")
    return {"prompt": prompt, "summary": target}


@pytest.fixture(scope="module")
def cli_checkpoint(tmp_path_factory):
    """Fine-tune quality-tiny on product-formatted pairs through the
    production training stack, save through the production Orbax path."""
    import jax
    import jax.numpy as jnp
    import optax

    from lmrs_tpu.config import model_preset
    from lmrs_tpu.data.tokenizer import ByteTokenizer
    from lmrs_tpu.models.loader import save_checkpoint
    from lmrs_tpu.models.transformer import init_params
    from lmrs_tpu.training.cli import batches, load_examples
    from lmrs_tpu.training.train import make_train_step

    cfg = model_preset("quality-tiny")
    rng = np.random.default_rng(0)
    pairs = []
    for _ in range(1000):
        transcript, topics = _make_cli_transcript(rng)
        pairs.extend(_product_format_pairs(transcript, topics))

    import tempfile
    from pathlib import Path as P

    with tempfile.TemporaryDirectory() as td:
        data_path = P(td) / "train.jsonl"
        data_path.write_text("\n".join(json.dumps(p) for p in pairs))
        seqs, masks = load_examples(str(data_path), ByteTokenizer())

    params = init_params(cfg, jax.random.PRNGKey(0))
    # warmup-cosine matters here: constant-lr runs oscillate and plateau at
    # held-out map ROUGE-L ~0.6 (calibration 2026-07-31); with decay the
    # same budget reaches ~0.94 map / 1.0 reduce (teacher-forced)
    steps = 1500
    sched = optax.warmup_cosine_decay_schedule(0.0, 3e-3, 100, steps,
                                               3e-3 * 0.02)
    optimizer = optax.adamw(sched)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(cfg, optimizer, None, masked=True)
    it = batches(seqs, masks, 8, 704, 0)
    loss = None
    for _ in range(steps):
        t, m = next(it)
        params, opt_state, loss = step_fn(params, opt_state,
                                          jnp.asarray(t), jnp.asarray(m))
    assert float(loss) < 0.25, f"CLI-format training failed: loss {float(loss)}"
    ckpt = tmp_path_factory.mktemp("cli_ckpt") / "quality-tiny"
    save_checkpoint(str(ckpt), params)
    return str(ckpt)


@pytest.fixture(scope="module")
def video_format_model():
    """Fine-tune quality-tiny ONLY on video-editor reduce pairs (exact
    product prompt format).  A dedicated model because byte-level digit
    COPYING (timestamps must be carried, not invented) is a capacity-
    hungry skill: diluted into the CLI fixture's multi-task mix it never
    emerges at any suite-affordable step count (calibration 2026-08-01:
    mixed training produced perfect sections but 0/6 exact stamps;
    dedicated 800-step training reaches loss ~0.01 with stamps copied)."""
    import jax
    import jax.numpy as jnp
    import optax

    from lmrs_tpu.config import model_preset
    from lmrs_tpu.data.tokenizer import ByteTokenizer
    from lmrs_tpu.models.transformer import init_params
    from lmrs_tpu.training.cli import batches, load_examples
    from lmrs_tpu.training.train import make_train_step

    cfg = model_preset("quality-tiny")
    rng = np.random.default_rng(0)
    pairs = [_video_reduce_pair(_video_reduce_items(rng))
             for _ in range(1500)]
    assert max(len(p["prompt"]) + len(p["summary"])
               for p in pairs) <= 820, "video pair overflows the crop"

    import tempfile
    from pathlib import Path as P

    with tempfile.TemporaryDirectory() as td:
        data_path = P(td) / "video.jsonl"
        data_path.write_text("\n".join(json.dumps(p) for p in pairs))
        seqs, masks = load_examples(str(data_path), ByteTokenizer())

    params = init_params(cfg, jax.random.PRNGKey(0))
    steps = 800
    sched = optax.warmup_cosine_decay_schedule(0.0, 3e-3, 100, steps,
                                               3e-3 * 0.02)
    optimizer = optax.adamw(sched)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(cfg, optimizer, None, masked=True)
    it = batches(seqs, masks, 8, 832, 0)
    loss = None
    for _ in range(steps):
        t, m = next(it)
        params, opt_state, loss = step_fn(params, opt_state,
                                          jnp.asarray(t), jnp.asarray(m))
    # calibration 2026-08-01: converges to ~0.01; stamp copying only
    # emerges well under ~0.06
    assert float(loss) < 0.05, f"video-format training failed: {float(loss)}"
    return cfg, ByteTokenizer(), params


def test_reduce_format_compliance(video_format_model):
    """The reference's core instruction-following contract, GENERATED
    (VERDICT r4 item 3): the trained model driven through the REAL
    video-editor reduce path (ResultAggregator.aggregate over real Chunk
    records — time-tagging, block formatting, engine wave) must emit the
    five ### sections in order, those five only, no preamble, with the
    input [H:MM:SS] timestamps carried through (result_aggregator.py:
    146-175's contract — previously the template was shipped but no test
    checked a generated document against it).  Three held-out recordings;
    format compliance must hold on ALL, exact stamp carry-through on >=2
    (calibration: 5/6 held-out fully compliant — one wobble allowed so a
    single hard example doesn't flake the gate)."""
    import re

    from lmrs_tpu.config import EngineConfig, ReduceConfig
    from lmrs_tpu.data.chunker import Chunk
    from lmrs_tpu.data.preprocessor import format_timestamp
    from lmrs_tpu.engine.executor import MapExecutor
    from lmrs_tpu.engine.jax_engine import JaxEngine
    from lmrs_tpu.reduce.aggregator import ResultAggregator

    cfg, tok, params = video_format_model
    ec = EngineConfig(backend="jax", scheduler="continuous", max_tokens=320,
                      max_batch_slots=2, seed=0, decode_block=16,
                      retry_delay=0.0)
    engine = JaxEngine(ec, cfg, params=params, tokenizer=tok)
    held = np.random.default_rng(777)
    stamps_ok = 0
    try:
        agg = ResultAggregator(MapExecutor(engine, ec),
                               ReduceConfig(temperature=0.0),
                               tokenizer="byte")
        for trial in range(3):
            items = _video_reduce_items(held)
            chunks = [
                Chunk(start_time=s, end_time=s + 40.0, chunk_index=i,
                      summary=f"[{format_timestamp(s)}] {topic}")
                for i, (s, topic) in enumerate(items)
            ]
            out = agg.aggregate(chunks,
                                prompt_template=VIDEO_REDUCE_TEMPLATE)
            text = out["final_summary"]
            positions = [text.find(f"### {s}") for s in VIDEO_SECTIONS]
            assert all(p >= 0 for p in positions), (trial, positions, text)
            assert positions == sorted(positions), (trial, positions, text)
            # exactly the five contract headers, no invented ones
            assert len(re.findall(r"### ", text)) == 5, (trial, text)
            # no greeting/preamble: the reply starts at the first header
            assert text.lstrip().startswith("### TIMELINE SUMMARY"), \
                (trial, text)
            if all(f"[{format_timestamp(s)}]" in text for s, _ in items):
                stamps_ok += 1
    finally:
        engine.shutdown()
    assert stamps_ok >= 2, f"timestamp carry-through {stamps_ok}/3"


@pytest.mark.parametrize("quant_args", [
    pytest.param([], id="fp"),
    pytest.param(["--quantize", "int8"], id="w8"),
    pytest.param(["--kv-quantize", "int8"], id="kv8"),
])
def test_cli_end_to_end_quality_gate(cli_checkpoint, tmp_path, monkeypatch,
                                     quant_args):
    """The PRODUCT surface, quality-gated (VERDICT r3 item 7): `lmrs`
    CLI -> preprocess -> chunk -> continuous-batching map -> reduce, with
    a trained checkpoint loaded via --checkpoint, scored against the
    held-out transcript's ground-truth topic summary.  Calibration
    (2026-07-31, CPU, fixed seeds): model 0.889 ROUGE-L end-to-end,
    extractive baseline 0.0 — gate at 0.45 is a format-or-content
    collapse tripwire, not a near-miss trap.

    Parametrized over the quantization flags (VERDICT r4 item 3): int8
    weights and int8 KV must keep LEARNED quality through the full CLI,
    not merely be throughput-measured on random weights."""
    from lmrs_tpu import cli
    from lmrs_tpu.eval.rouge import rouge_l

    monkeypatch.setenv("TEMPERATURE", "0.0")  # greedy map (env-config path)
    # generation budget via the reference's env knob (MAX_TOKENS,
    # SURVEY.md §5.6): the default 1000 would push the scheduler's prompt
    # truncation limit below the ~460-byte product prompts at this window
    monkeypatch.setenv("MAX_TOKENS", "96")
    held, topics = _make_cli_transcript(np.random.default_rng(4242))
    truth = " " + ", ".join(topics) + "."

    inp = tmp_path / "transcript.json"
    inp.write_text(json.dumps(held))
    out = tmp_path / "summary.txt"
    mapf = tmp_path / "map_prompt.txt"
    mapf.write_text(MAP_TEMPLATE)
    redf = tmp_path / "reduce_prompt.txt"
    redf.write_text(REDUCE_TEMPLATE)

    rc = cli.main([
        "--input", str(inp), "--output", str(out),
        "--backend", "jax", "--model", "quality-tiny",
        "--checkpoint", cli_checkpoint, "--tokenizer", "byte",
        "--max-tokens-per-chunk", str(CLI_CHUNK_TOKENS),
        "--overlap-tokens", "0",
        "--prompt-file", str(mapf),
        "--aggregator-prompt-file", str(redf),
        "--report", "--quiet",
        *quant_args,
    ])
    assert rc == 0
    text = out.read_text()
    score = rouge_l(text, truth)["f"]
    assert score >= 0.45, (score, text, truth)
    report = json.loads((tmp_path / "summary.txt.report.json").read_text())
    assert report["num_chunks"] >= 2, "held-out transcript must multi-chunk"
    assert report["failed_requests"] == 0
