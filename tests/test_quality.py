"""Offline quality gate (VERDICT r1 item 4): the stack must demonstrably
SUMMARIZE, not just stream tokens.

Two layers, both scored with the in-tree ROUGE harness against stored /
ground-truth baselines:

1. ``test_parity_vs_committed_baseline`` — the full pipeline on the real
   7.4 h example transcript scored against the committed curated baseline
   (examples/baseline_summary.json).  The mock engine is extractive, so
   the absolute score is modest; the gate is a calibrated regression
   tripwire (measured 0.042 ROUGE-L / 0.084 ROUGE-1 on 2026-07-30 — a
   format or content collapse drops it to ~0).
2. ``test_trained_model_beats_extractive_baseline`` — the REAL gate: a
   model is fine-tuned through the production training stack on synthetic
   transcript→summary pairs (eval/synthetic.py), held-out prompts are
   decoded through the production continuous-batching engine, and the
   mean ROUGE-L against ground truth must clear a non-trivial threshold
   AND beat the trivial lead-1 extractive baseline by a wide margin.
   Calibration (2026-07-30, CPU, fixed seeds): model 0.396, extractive
   0.048 — gates set at 0.30 and 3x.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

BASELINE_FIXTURE = (Path(__file__).parent.parent / "examples"
                    / "baseline_summary.json")


def test_parity_vs_committed_baseline(example_transcript):
    from lmrs_tpu.config import EngineConfig, PipelineConfig
    from lmrs_tpu.eval.parity import load_baseline, run_parity

    baseline = load_baseline(BASELINE_FIXTURE)
    assert len(baseline.split()) > 150  # a real summary, not a stub
    cfg = PipelineConfig(engine=EngineConfig(backend="mock"))
    report = run_parity(example_transcript, baseline, cfg, threshold=0.02)
    assert report.passed, report.to_dict()
    assert report.rouge1_f >= 0.04, report.to_dict()


@pytest.fixture(scope="module")
def trained_summarizer():
    """Fine-tune the tiny byte-level model on synthetic pairs through the
    production path: JSONL -> training.cli.load_examples (loss masked to
    the summary) -> make_train_step."""
    import jax
    import jax.numpy as jnp
    import optax

    from lmrs_tpu.config import ModelConfig
    from lmrs_tpu.data.tokenizer import ByteTokenizer
    from lmrs_tpu.eval.synthetic import make_dataset
    from lmrs_tpu.models.transformer import init_params
    from lmrs_tpu.training.cli import batches, load_examples
    from lmrs_tpu.training.train import make_train_step

    cfg = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                      dtype="float32")
    tok = ByteTokenizer()
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as td:
        data_path = Path(td) / "train.jsonl"
        data_path.write_text("\n".join(
            json.dumps({"prompt": ex["prompt"], "summary": ex["summary"]})
            for ex in make_dataset(192, seed=0)))
        seqs, masks = load_examples(str(data_path), tok)

    params = init_params(cfg, jax.random.PRNGKey(0))
    optimizer = optax.adamw(4e-3)
    opt_state = optimizer.init(params)
    step_fn = make_train_step(cfg, optimizer, None, masked=True)
    it = batches(seqs, masks, 16, 320, 0)
    loss = None
    for _ in range(200):
        t, m = next(it)
        params, opt_state, loss = step_fn(params, opt_state,
                                          jnp.asarray(t), jnp.asarray(m))
    assert float(loss) < 0.5, f"training failed to converge: loss {float(loss)}"
    return cfg, tok, params


def test_trained_model_beats_extractive_baseline(trained_summarizer):
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine
    from lmrs_tpu.eval.rouge import rouge_l
    from lmrs_tpu.eval.synthetic import extractive_baseline, make_dataset

    cfg, tok, params = trained_summarizer
    engine = JaxEngine(
        EngineConfig(backend="jax", scheduler="continuous", max_tokens=48,
                     max_batch_slots=4, seed=0, decode_block=8),
        cfg, params=params, tokenizer=tok)
    # Seed-disjoint is not prompt-disjoint (ADVICE r2): with 12 topics and
    # 2-3 draws per example, a held-out prompt can collide verbatim with a
    # training prompt.  Filter exact-prompt overlap so the gate measures
    # generalization, drawing extra candidates to keep the set at 8.
    train_prompts = {ex["prompt"] for ex in make_dataset(192, seed=0)}
    held = [ex for ex in make_dataset(32, seed=999)
            if ex["prompt"] not in train_prompts][:8]
    assert len(held) == 8, "synthetic generator collided on all candidates"
    reqs = [GenerationRequest(prompt=ex["prompt"], request_id=i,
                              temperature=0.0, max_new_tokens=48)
            for i, ex in enumerate(held)]
    outs = engine.generate_batch(reqs)
    engine.shutdown()

    model_f = [rouge_l(o.text, ex["summary"])["f"]
               for ex, o in zip(held, outs)]
    extract_f = [rouge_l(extractive_baseline(ex["prompt"]), ex["summary"])["f"]
                 for ex in held]
    mean_model = float(np.mean(model_f))
    mean_extract = float(np.mean(extract_f))
    # non-trivial absolute gate + wide margin over the trivial baseline
    assert mean_model >= 0.30, (mean_model, model_f)
    assert mean_model > 3 * mean_extract, (mean_model, mean_extract)


def test_trained_model_quality_survives_kv_int8(trained_summarizer):
    """The REAL numerics gate for kv_quantize=int8 (per-slot/head/channel
    scales, ops/quant.py KV section): the fine-tuned model decoded through
    int8 KV pages must keep its learned-summarization quality, not merely
    not crash.  A scale-wiring bug (wrong rows, wrong channel axis) floors
    ROUGE-L to extractive-baseline territory instantly."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine
    from lmrs_tpu.eval.rouge import rouge_l
    from lmrs_tpu.eval.synthetic import make_dataset

    cfg, tok, params = trained_summarizer
    engine = JaxEngine(
        EngineConfig(backend="jax", scheduler="continuous", max_tokens=48,
                     max_batch_slots=4, seed=0, decode_block=8,
                     page_size=32, kv_quantize="int8"),
        cfg, params=params, tokenizer=tok)
    train_prompts = {ex["prompt"] for ex in make_dataset(192, seed=0)}
    held = [ex for ex in make_dataset(32, seed=999)
            if ex["prompt"] not in train_prompts][:8]
    reqs = [GenerationRequest(prompt=ex["prompt"], request_id=i,
                              temperature=0.0, max_new_tokens=48)
            for i, ex in enumerate(held)]
    outs = engine.generate_batch(reqs)
    engine.shutdown()
    model_f = [rouge_l(o.text, ex["summary"])["f"]
               for ex, o in zip(held, outs)]
    mean_model = float(np.mean(model_f))
    # same absolute gate as the full-precision test: int8 KV must not cost
    # the learned behavior (small per-example wobble is expected)
    assert mean_model >= 0.28, (mean_model, model_f)
