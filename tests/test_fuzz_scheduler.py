"""Seeded scheduler fuzz: bookkeeping invariants over randomized
adversarial workloads.

The latent-bug class this hunts (see the round-1 SMEM OOB fix, commit
e763805): host slot-state bookkeeping — stale lengths on slot reuse,
preemption/requeue, tight-pool growth, packed-vs-unpacked routing — only
breaks on *combinations* no hand-written scenario covers.

Exact cross-scheduler text equality is deliberately NOT asserted here: a
random-init model's greedy argmax is knife-edge, so different dispatch
bucketing (different pad shapes → different f32 reduction order) can flip
near-ties between the static and continuous paths without any bug — the
single calibrated shape in test_greedy_matches_static_scheduler covers
that equivalence.  What IS asserted, per scenario:

* determinism: the SAME continuous config on the same mix twice produces
  token-identical results — shape-identical dispatches have identical
  numerics, so any divergence is host-state corruption (stale slot
  arrays, preemption order, page recycling);
* the request contract: no errors, completion budgets respected, stop
  strings absent from returned text, every request finishes with a valid
  reason;
* accounting sanity: decode token counts match completion totals minus
  the prefill-sampled first tokens (bounded below), occupancy in [0, 1].
"""

from __future__ import annotations

import random

import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine

WORDS = ("plan kernel budget review latency timeline shipping quarter "
         "inference engine design hiring allocation targets").split()


def _model(dim: int = 64, hidden: int = 128) -> ModelConfig:
    return ModelConfig(vocab_size=512, dim=dim, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=hidden, max_seq_len=256,
                       dtype="float32")


def _requests(rng: random.Random, n: int) -> list[GenerationRequest]:
    reqs = []
    for i in range(n):
        n_words = rng.choice((2, 8, 30, 80))
        prompt = " ".join(rng.choice(WORDS) for _ in range(n_words))
        stop = ("ing",) if rng.random() < 0.3 else ()
        reqs.append(GenerationRequest(
            prompt=prompt, request_id=i, temperature=0.0,
            max_new_tokens=rng.choice((1, 3, 9, 20)), stop=stop))
    return reqs


def _check_contract(reqs, out):
    by_id = {r.request_id: r for r in reqs}
    assert [r.request_id for r in out] == [r.request_id for r in reqs]
    for res in out:
        req = by_id[res.request_id]
        assert res.error is None, res
        assert res.finish_reason in ("stop", "length")
        assert res.completion_tokens <= req.max_new_tokens
        for s in req.stop:
            assert s not in res.text


@pytest.mark.parametrize("seed", [11, 23, 37, 59])
def test_fuzzed_continuous_scheduler_is_deterministic(seed):
    rng = random.Random(seed)
    mc = _model()
    n_requests = rng.randint(1, 9)
    scenario = dict(
        max_batch_slots=rng.choice((1, 2, 3)),
        page_size=rng.choice((16, 32)),
        # small budgets force on-demand growth + youngest-slot preemption;
        # 1 = worst-case pool (never preempts)
        num_pages=rng.choice((1, 24, 48)),
        decode_block=rng.choice((2, 5, 8)),
        prefill_chunk=rng.choice((64, 4096)),  # chunked vs one-dispatch
    )
    reqs = _requests(rng, n_requests)

    runs = []
    metrics = []
    for _ in range(2):
        eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=24, seed=0, **scenario), mc)
        out = eng.generate_batch(reqs)
        _check_contract(reqs, out)
        runs.append([(r.text, r.finish_reason, r.completion_tokens)
                     for r in out])
        m = eng._scheduler.metrics
        metrics.append(dict(m))
        assert 0.0 <= m["occupancy_sum"] <= m["decode_dispatches"] + 1e-9
        eng.shutdown()
    assert runs[0] == runs[1], (scenario, metrics)


def _prefix_requests(rng: random.Random, n: int) -> list[GenerationRequest]:
    """Prefix-sharing adversarial mix: requests draw one of three shared
    preambles (or none), diverge at random depths, and carry varied
    budgets — shared / partial / disjoint prefixes all collide in the
    radix tree at page boundaries."""
    preambles = [
        "shared preamble alpha " * rng.randint(1, 4),
        "shared preamble beta " * rng.randint(1, 4),
        "",
    ]
    reqs = []
    for i in range(n):
        pre = rng.choice(preambles)
        # partial sharing: sometimes truncate the preamble mid-page
        if pre and rng.random() < 0.4:
            pre = pre[: rng.randrange(1, len(pre))]
        body = " ".join(rng.choice(WORDS) for _ in range(rng.choice((2, 10, 40))))
        hint = len(pre) if (pre and rng.random() < 0.5) else None
        reqs.append(GenerationRequest(
            prompt=pre + body, request_id=i, temperature=0.0,
            max_new_tokens=rng.choice((1, 4, 12)), cache_prefix=hint))
    return reqs


def _check_pool_invariants(sched):
    """Post-run pool accounting: every page is either free (refcount 0) or
    retained by the prefix cache (refcount exactly 1 — no live sequences
    remain), the cache's page count agrees with the allocator, and no page
    is both free and referenced."""
    alloc = sched.cache.allocator
    cache = sched._prefix_cache
    cached = cache.cached_pages if cache else 0
    usable = sched.cache.num_pages - 1
    assert alloc.free_count == usable - cached, (alloc.free_count, cached)
    refs = [alloc.refcount(p) for p in range(1, sched.cache.num_pages)]
    assert sum(1 for r in refs if r > 0) == cached
    assert all(r in (0, 1) for r in refs), refs  # no leaked holders
    if cache:
        # every page the tree holds is live in the allocator
        stack = [cache.root]
        tree_pages = []
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            tree_pages.extend(node.pages)
        assert len(tree_pages) == len(set(tree_pages)) == cached
        assert all(alloc.refcount(p) == 1 for p in tree_pages)


@pytest.mark.parametrize("seed", [5, 17, 41])
def test_fuzzed_prefix_sharing_mixes(seed):
    """Randomized shared/partial/disjoint prefix mixes under page pressure:
    determinism across identical runs, the request contract, and pool
    accounting invariants (refcounts sum, no page both free and referenced)
    — with eviction exercised via small pools."""
    rng = random.Random(seed)
    mc = _model()
    scenario = dict(
        max_batch_slots=rng.choice((2, 3)),
        page_size=16,
        # small budgets force growth, preemption AND cache eviction under
        # pressure; 1 = worst-case pool (cache grows until close)
        num_pages=rng.choice((1, 20, 40)),
        decode_block=rng.choice((2, 6)),
        prefill_chunk=rng.choice((64, 4096)),
        prefix_cache_max_pages=rng.choice((0, 8)),
    )
    reqs = _prefix_requests(rng, rng.randint(4, 10))

    runs = []
    for _ in range(2):
        eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=16, seed=0, **scenario), mc)
        out = eng.generate_batch(reqs)
        _check_contract(reqs, out)
        sched = eng._scheduler
        assert sched._prefix_cache is not None
        _check_pool_invariants(sched)
        m = sched.metrics
        assert m["prefix_queries"] >= len(reqs)
        assert m["prefix_tokens_reused"] >= 0
        runs.append([(r.text, r.finish_reason, r.completion_tokens)
                     for r in out])
        eng.shutdown()
    assert runs[0] == runs[1], scenario


def test_fuzzed_prefix_cache_on_off_parity():
    """Greedy outputs must be token-identical with the prefix cache on and
    off across a randomized shared-prefix mix (the cache may only change
    WHERE KV lives, never its values)."""
    rng = random.Random(77)
    mc = _model()
    reqs = _prefix_requests(rng, 8)
    texts = {}
    for on in (True, False):
        eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=16, seed=0, max_batch_slots=2,
                                     page_size=16, decode_block=4,
                                     prefix_cache=on), mc)
        out = eng.generate_batch(reqs)
        _check_contract(reqs, out)
        if on:
            assert eng._scheduler.metrics["prefix_hits"] > 0
        texts[on] = [r.text for r in out]
        eng.shutdown()
    assert texts[True] == texts[False]


@pytest.mark.parametrize("seed", [13, 47])
def test_fuzzed_mixed_admission_bursts(seed, monkeypatch):
    """Mixed dispatch (ISSUE 11) under randomized admission bursts
    MID-DECODE: on_result callbacks submit fresh batches into the live
    stream, so new prompts are admitted while earlier requests decode —
    exactly the regime the fused mixed step serves.  Asserts, per seed:

    * greedy token-identity LMRS_MIXED=0 vs 1 over the identical burst
      workload (the mixed arm must actually have mixed);
    * determinism: the mixed arm twice is token-identical;
    * the request contract and the scheduler auditor, clean."""
    rng = random.Random(seed)
    mc = _model()
    scenario = dict(
        max_batch_slots=rng.choice((2, 3)),
        page_size=16,
        num_pages=rng.choice((1, 32)),  # 32 = real pressure mid-mix
        decode_block=rng.choice((2, 4)),
        prefill_chunk=rng.choice((64, 4096)),
        mixed_token_budget=rng.choice((48, 256)),
    )
    initial = _requests(rng, rng.randint(2, 4))
    # pre-generated burst batches: submitted when pinned request ids
    # complete, so the submission SCHEDULE is identical across arms
    bursts = [_requests(random.Random(seed + 1 + i), rng.randint(1, 3))
              for i in range(2)]
    for i, batch in enumerate(bursts):
        for r in batch:
            r.request_id += 100 * (i + 1)
    trigger = {initial[0].request_id: 0,
               initial[-1].request_id: 1}

    def run(mixed: str):
        monkeypatch.setenv("LMRS_MIXED", mixed)
        eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=24, seed=0, **scenario), mc)
        fired = set()

        def on_result(res, submit):
            i = trigger.get(res.request_id)
            if i is not None and i not in fired:
                fired.add(i)
                submit(list(bursts[i]))

        out = eng.generate_batch(list(initial), on_result=on_result)
        assert eng._scheduler.audit() == []
        m = dict(eng._scheduler.metrics)
        eng.shutdown()
        every = initial + [r for b in bursts for r in b]
        assert {r.request_id for r in out} == {r.request_id for r in every}
        by_id = {r.request_id: r for r in every}
        for res in out:
            req = by_id[res.request_id]
            assert res.error is None, res
            assert res.finish_reason in ("stop", "length")
            assert res.completion_tokens <= req.max_new_tokens
        return sorted((r.request_id, r.text, r.finish_reason,
                       r.completion_tokens) for r in out), m

    base, m_off = run("0")
    assert m_off["mixed_dispatches"] == 0
    mixed1, m_on = run("1")
    mixed2, _ = run("1")
    assert mixed1 == mixed2, scenario  # determinism
    assert mixed1 == base, scenario    # greedy A/B identity
    # the bursts landed mid-decode, so the mixed arm must have mixed
    assert m_on["mixed_dispatches"] > 0, scenario
    assert m_on["prefill_tokens_piggybacked"] > 0, scenario


@pytest.mark.parametrize("seed", [19, 53])
def test_fuzzed_rpa_admission_bursts(seed, monkeypatch):
    """Ragged-span dispatch (ISSUE 16) under the same randomized
    mid-decode admission bursts: greedy token-identity LMRS_RPA=0 vs 1
    (the span arm must actually dispatch span programs), span-arm
    determinism, the request contract, and a clean auditor — the fuzzed
    counterpart of the hand-written A/B matrix in test_rpa.py."""
    rng = random.Random(seed)
    mc = _model()
    scenario = dict(
        max_batch_slots=rng.choice((2, 3)),
        page_size=16,
        num_pages=rng.choice((1, 32)),
        decode_block=rng.choice((2, 4)),
        prefill_chunk=rng.choice((64, 4096)),
        mixed_token_budget=rng.choice((48, 256)),
        speculate_k=rng.choice((0, 3)),
    )
    initial = _requests(rng, rng.randint(2, 4))
    bursts = [_requests(random.Random(seed + 1 + i), rng.randint(1, 3))
              for i in range(2)]
    for i, batch in enumerate(bursts):
        for r in batch:
            r.request_id += 100 * (i + 1)
    trigger = {initial[0].request_id: 0,
               initial[-1].request_id: 1}

    def run(rpa: str):
        monkeypatch.setenv("LMRS_RPA", rpa)
        eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=24, seed=0, **scenario), mc)
        fired = set()

        def on_result(res, submit):
            i = trigger.get(res.request_id)
            if i is not None and i not in fired:
                fired.add(i)
                submit(list(bursts[i]))

        out = eng.generate_batch(list(initial), on_result=on_result)
        assert eng._scheduler.audit() == []
        m = dict(eng._scheduler.metrics)
        eng.shutdown()
        every = initial + [r for b in bursts for r in b]
        assert {r.request_id for r in out} == {r.request_id for r in every}
        by_id = {r.request_id: r for r in every}
        for res in out:
            req = by_id[res.request_id]
            assert res.error is None, res
            assert res.finish_reason in ("stop", "length")
            assert res.completion_tokens <= req.max_new_tokens
        return sorted((r.request_id, r.text, r.finish_reason,
                       r.completion_tokens) for r in out), m

    base, m_off = run("0")
    assert m_off["rpa_dispatches"] == 0
    span1, m_on = run("1")
    span2, _ = run("1")
    assert span1 == span2, scenario  # determinism
    assert span1 == base, scenario   # greedy A/B identity
    assert m_on["rpa_dispatches"] > 0, scenario


@pytest.mark.parametrize("seed", [29, 71])
def test_fuzzed_qos_preemption_heavy_mix(seed, monkeypatch):
    """Fair-share admission + QoS preemption (ISSUE 17) under a
    preemption-heavy randomized multi-tenant mix: a tight page pool with
    several tenants and both priority classes, so slots preempt and the
    armed policy actually exercises its victim rule.  Asserts, per seed:

    * greedy token-identity LMRS_QOS=0 vs 1 over the identical workload
      (QoS changes admission and victim ORDER, never tokens);
    * determinism: the armed arm twice is token-identical;
    * preemption really happened in both arms (the mix is not vacuous);
    * the scheduler auditor and ledger conservation, clean through the
      preemption/requeue churn: per-tenant rollups sum to totals and no
      entry stays live."""
    rng = random.Random(seed)
    mc = _model()
    # short prompts (all slots admit at once) + long decodes into a pool
    # too small for every slot's worst-case growth: the collision that
    # triggers preemption (the test_scheduler.py pressure recipe)
    scenario = dict(
        max_batch_slots=4,
        page_size=16,
        num_pages=10,
        decode_block=rng.choice((2, 4)),
        prefill_chunk=rng.choice((64, 4096)),
    )
    tenants = ("noisy", "quiet", "bulk")
    reqs = []
    for i in range(rng.randint(6, 9)):
        n_words = rng.choice((4, 8, 12))
        reqs.append(GenerationRequest(
            prompt=" ".join(rng.choice(WORDS) for _ in range(n_words)),
            request_id=i, temperature=0.0,
            max_new_tokens=40,  # long growth: every slot crosses pages
            tenant=rng.choice(tenants),
            qos_class=rng.choice(("interactive", "batch"))))

    def run(qos: str):
        monkeypatch.setenv("LMRS_QOS", qos)
        eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=40, seed=0, **scenario), mc)
        out = eng.generate_batch(list(reqs))
        _check_contract(reqs, out)
        sched = eng._scheduler
        assert sched.audit() == []
        preempts = int(sched._c_preemptions.value)
        usage = eng.usage_report()
        qos_rep = eng.qos_report()
        eng.shutdown()
        assert usage["live_requests"] == 0
        tenant_dev = sum(r["device_seconds"]
                         for r in usage["tenants"].values())
        # 1e-6: report values are rounded per tenant before summing
        assert abs(tenant_dev - usage["totals"]["device_seconds"]) < 1e-6
        assert set(usage["tenants"]) == {r.tenant for r in reqs}
        return ([(r.text, r.finish_reason, r.completion_tokens)
                 for r in out], preempts, qos_rep)

    base, pre_off, rep_off = run("0")
    assert rep_off == {"object": "qos", "enabled": False}
    armed1, pre_on, rep_on = run("1")
    armed2, _, _ = run("1")
    assert armed1 == armed2, scenario  # determinism
    assert armed1 == base, scenario    # greedy A/B identity
    assert rep_on["enabled"] is True
    # the pool was tight enough that both arms actually preempted
    assert pre_off > 0 and pre_on > 0, (scenario, pre_off, pre_on)


def test_fuzzed_slot_reuse_with_interpret_kernels(monkeypatch):
    """Slot recycling + varied lengths through the REAL kernel path
    (interpret): the exact conditions of the r1 stale-length SMEM bug —
    many short requests through few slots, lengths crossing page
    boundaries, pool pressure — twice, token-identical."""
    monkeypatch.setenv("LMRS_FORCE_KERNELS", "interpret")
    rng = random.Random(101)
    mc = _model(dim=512, hidden=256)  # hd=128: kernel gate on
    reqs = _requests(rng, 7)

    runs = []
    for _ in range(2):
        eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=24, seed=0, max_batch_slots=2,
                                     page_size=16, num_pages=40,
                                     decode_block=4), mc)
        assert eng._scheduler._use_ragged
        out = eng.generate_batch(reqs)
        _check_contract(reqs, out)
        runs.append([r.text for r in out])
        eng.shutdown()
    assert runs[0] == runs[1]
