"""Seeded scheduler fuzz: bookkeeping invariants over randomized
adversarial workloads.

The latent-bug class this hunts (see the round-1 SMEM OOB fix, commit
e763805): host slot-state bookkeeping — stale lengths on slot reuse,
preemption/requeue, tight-pool growth, packed-vs-unpacked routing — only
breaks on *combinations* no hand-written scenario covers.

Exact cross-scheduler text equality is deliberately NOT asserted here: a
random-init model's greedy argmax is knife-edge, so different dispatch
bucketing (different pad shapes → different f32 reduction order) can flip
near-ties between the static and continuous paths without any bug — the
single calibrated shape in test_greedy_matches_static_scheduler covers
that equivalence.  What IS asserted, per scenario:

* determinism: the SAME continuous config on the same mix twice produces
  token-identical results — shape-identical dispatches have identical
  numerics, so any divergence is host-state corruption (stale slot
  arrays, preemption order, page recycling);
* the request contract: no errors, completion budgets respected, stop
  strings absent from returned text, every request finishes with a valid
  reason;
* accounting sanity: decode token counts match completion totals minus
  the prefill-sampled first tokens (bounded below), occupancy in [0, 1].
"""

from __future__ import annotations

import random

import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine

WORDS = ("plan kernel budget review latency timeline shipping quarter "
         "inference engine design hiring allocation targets").split()


def _model(dim: int = 64, hidden: int = 128) -> ModelConfig:
    return ModelConfig(vocab_size=512, dim=dim, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=hidden, max_seq_len=256,
                       dtype="float32")


def _requests(rng: random.Random, n: int) -> list[GenerationRequest]:
    reqs = []
    for i in range(n):
        n_words = rng.choice((2, 8, 30, 80))
        prompt = " ".join(rng.choice(WORDS) for _ in range(n_words))
        stop = ("ing",) if rng.random() < 0.3 else ()
        reqs.append(GenerationRequest(
            prompt=prompt, request_id=i, temperature=0.0,
            max_new_tokens=rng.choice((1, 3, 9, 20)), stop=stop))
    return reqs


def _check_contract(reqs, out):
    by_id = {r.request_id: r for r in reqs}
    assert [r.request_id for r in out] == [r.request_id for r in reqs]
    for res in out:
        req = by_id[res.request_id]
        assert res.error is None, res
        assert res.finish_reason in ("stop", "length")
        assert res.completion_tokens <= req.max_new_tokens
        for s in req.stop:
            assert s not in res.text


@pytest.mark.parametrize("seed", [11, 23, 37, 59])
def test_fuzzed_continuous_scheduler_is_deterministic(seed):
    rng = random.Random(seed)
    mc = _model()
    n_requests = rng.randint(1, 9)
    scenario = dict(
        max_batch_slots=rng.choice((1, 2, 3)),
        page_size=rng.choice((16, 32)),
        # small budgets force on-demand growth + youngest-slot preemption;
        # 1 = worst-case pool (never preempts)
        num_pages=rng.choice((1, 24, 48)),
        decode_block=rng.choice((2, 5, 8)),
        prefill_chunk=rng.choice((64, 4096)),  # chunked vs one-dispatch
    )
    reqs = _requests(rng, n_requests)

    runs = []
    metrics = []
    for _ in range(2):
        eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=24, seed=0, **scenario), mc)
        out = eng.generate_batch(reqs)
        _check_contract(reqs, out)
        runs.append([(r.text, r.finish_reason, r.completion_tokens)
                     for r in out])
        m = eng._scheduler.metrics
        metrics.append(dict(m))
        assert 0.0 <= m["occupancy_sum"] <= m["decode_dispatches"] + 1e-9
        eng.shutdown()
    assert runs[0] == runs[1], (scenario, metrics)


def test_fuzzed_slot_reuse_with_interpret_kernels(monkeypatch):
    """Slot recycling + varied lengths through the REAL kernel path
    (interpret): the exact conditions of the r1 stale-length SMEM bug —
    many short requests through few slots, lengths crossing page
    boundaries, pool pressure — twice, token-identical."""
    monkeypatch.setenv("LMRS_FORCE_KERNELS", "interpret")
    rng = random.Random(101)
    mc = _model(dim=512, hidden=256)  # hd=128: kernel gate on
    reqs = _requests(rng, 7)

    runs = []
    for _ in range(2):
        eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=24, seed=0, max_batch_slots=2,
                                     page_size=16, num_pages=40,
                                     decode_block=4), mc)
        assert eng._scheduler._use_ragged
        out = eng.generate_batch(reqs)
        _check_contract(reqs, out)
        runs.append([r.text for r in out])
        eng.shutdown()
    assert runs[0] == runs[1]
