"""Roofline accounting (utils/perf_model.py) — the MFU/bandwidth numbers in
bench.py are only as honest as these counts."""

import jax
import pytest

from lmrs_tpu.config import ModelConfig, model_preset
from lmrs_tpu.models.transformer import init_params, param_count
from lmrs_tpu.utils.perf_model import (
    chip_spec, decode_step_bytes, kv_bytes_per_token, matmul_params,
    prefill_flops, weight_bytes,
)


def test_matmul_params_matches_initialized_tree():
    """matmul_params + norm scales == param_count for a tied-embedding
    model (the tied LM head is the embedding matrix, counted once in the
    tree but doing matmul work)."""
    cfg = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                      dtype="float32")
    total = param_count(init_params(cfg, jax.random.PRNGKey(0)))
    norms = cfg.n_layers * 2 * cfg.dim + cfg.dim
    assert matmul_params(cfg) + norms == total


def test_matmul_params_untied_head():
    cfg = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                      dtype="float32", tie_embeddings=False)
    total = param_count(init_params(cfg, jax.random.PRNGKey(0)))
    norms = cfg.n_layers * 2 * cfg.dim + cfg.dim
    embed = cfg.vocab_size * cfg.dim  # lookup-only, not a matmul
    assert matmul_params(cfg) + norms + embed == total


def test_bench_1b_scale():
    """The bench model must actually be >= 1B params (VERDICT r1 item 1)."""
    cfg = model_preset("bench-1b")
    assert matmul_params(cfg) >= 1_000_000_000
    assert cfg.hd % 128 == 0  # ragged-kernel eligible


def test_prefill_flops_components():
    cfg = model_preset("bench-1b")
    s = 2048
    fl = prefill_flops(cfg, s)
    dense = 2.0 * matmul_params(cfg) * s
    attn = 2.0 * cfg.n_layers * s * s * cfg.hd * cfg.n_heads
    assert fl == pytest.approx(dense + attn)
    # gathered LM head shrinks the vocab matmul, nothing else
    fl_packed = prefill_flops(cfg, s, head_tokens=24)
    assert fl - fl_packed == pytest.approx(
        2.0 * (s - 24) * cfg.dim * cfg.vocab_size)


def test_decode_bytes_components():
    cfg = model_preset("bench-1b")
    live = 24 * 1536
    assert decode_step_bytes(cfg, live) == pytest.approx(
        weight_bytes(cfg) + live * kv_bytes_per_token(cfg))
    # int8 halves the matmul-weight stream
    assert weight_bytes(cfg, quantized=True) == pytest.approx(
        matmul_params(cfg))


def test_chip_spec_fallback_is_sane():
    spec = chip_spec()  # CPU test backend -> unknown kind, v5e fallback
    assert spec.peak_flops > 0 and spec.peak_hbm_bw > 0
