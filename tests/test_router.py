"""Cross-process serving (VERDICT r4 item 7): two REAL lmrs-serve OS
processes — each with its own continuous-batching scheduler — fed from one
queue by ``serving/router.py``'s RouterEngine.

This is the multi-host serving deployment in miniature: per-host server
processes (DCN would carry only requests/completions), a router fanning one
request list over the fleet, cancellation crossing the process boundary as
a hangup, and per-host failure degrading instead of killing the wave.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import random
import threading
import time
import urllib.request

import pytest

from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.serving.router import RouterEngine


from tests.conftest import free_port as _free_port


def _wait_healthy(url: str, proc, deadline_s: float = 180.0) -> None:
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if proc.poll() is not None:
            raise RuntimeError(
                f"worker died rc={proc.returncode}: {proc.stderr.read().decode()[-2000:]}")
        try:
            with urllib.request.urlopen(f"{url}/healthz", timeout=2) as r:
                if r.status == 200:
                    return
        except OSError:
            time.sleep(0.3)
    raise TimeoutError(f"{url} never became healthy")


def _host_metrics(url: str) -> dict:
    with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
        return json.loads(r.read())


def _spawn_mock_worker(port: int) -> subprocess.Popen:
    """One mock-backend lmrs-serve process (the shared worker-spawn used
    by the fleet tests that don't need a real scheduler)."""
    return subprocess.Popen(
        [sys.executable, "-m", "lmrs_tpu.serving.cli",
         "--backend", "mock", "--port", str(port), "-q"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd="/root/repo",
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _teardown(procs) -> None:
    for proc in procs:
        proc.terminate()
    for proc in procs:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


@pytest.fixture(scope="module")
def cluster():
    """Two lmrs-serve processes with REAL jax continuous schedulers
    (quality-tiny byte model — the same preset the CLI quality gate
    compiles on CPU) + a RouterEngine over both."""
    ports = [_free_port(), _free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "lmrs_tpu.serving.cli",
             "--backend", "jax", "--model", "quality-tiny",
             "--tokenizer", "byte", "--port", str(p),
             "--batch-slots", "2", "--max-tokens-cap", "1024", "-q"],
            env=env, cwd="/root/repo",
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        for p in ports
    ]
    router = RouterEngine(urls, timeout_s=300.0)
    try:
        for url, proc in zip(urls, procs):
            _wait_healthy(url, proc)
        yield urls, procs, router
    finally:
        router.shutdown()
        _teardown(procs)


def test_wave_fans_over_both_processes(cluster):
    """One wave through the router completes on BOTH worker processes,
    order preserved, per-request accounting intact."""
    urls, _, router = cluster
    reqs = [GenerationRequest(prompt=f"router fan probe {i}", request_id=i,
                              temperature=0.0, max_new_tokens=6)
            for i in range(6)]
    out = router.generate_batch(reqs)
    assert [r.request_id for r in out] == list(range(6))
    assert all(r.error is None for r in out)
    assert all(0 < r.completion_tokens <= 6 for r in out)
    for url in urls:  # both schedulers actually decoded
        m = _host_metrics(url)
        assert m["engine"]["decode_tokens"] > 0, f"{url} served nothing"
        assert m["http_requests"] > 0


def test_streamed_matches_nonstreamed_greedy(cluster):
    """on_tokens through the router consumes the remote SSE stream; greedy
    text must match the non-streamed wire path (identical weights + seed
    on both workers, so host routing cannot change the answer)."""
    _, _, router = cluster
    req = dict(prompt="stream parity probe", temperature=0.0,
               max_new_tokens=8)
    plain = router.generate_batch([GenerationRequest(request_id=0, **req)])[0]
    deltas: list[str] = []
    streamed = router.generate_batch(
        [GenerationRequest(request_id=1, **req)],
        on_tokens=lambda rid, d: deltas.append(d))[0]
    assert plain.error is None and streamed.error is None
    assert streamed.text == plain.text
    assert "".join(deltas) == streamed.text


def test_cancel_crosses_process_boundary(cluster):
    """router.cancel() hangs up the in-flight socket; the worker's
    disconnect detection must cancel the request REMOTELY (its scheduler
    records the abort and frees the slot) while the router reports
    finish_reason='cancelled' locally."""
    urls, _, router = cluster
    cancelled_before = sum(
        _host_metrics(u)["engine"].get("cancelled", 0) for u in urls)

    result = {}

    def run() -> None:
        result["res"] = router.generate_batch(
            [GenerationRequest(prompt="cancel me over the wire",
                               request_id=77, temperature=0.0,
                               max_new_tokens=900)])[0]

    tokens_before = {u: _host_metrics(u)["engine"]["decode_tokens"]
                     for u in urls}
    t = threading.Thread(target=run)
    t.start()
    # cancel once a worker is provably mid-decode on THIS request: its
    # decode_tokens counter grows past the pre-test snapshot (900 tokens /
    # decode_block 16 = 56 block boundaries for the sweep to land on —
    # the budget is deliberately large so a fast warm decode cannot
    # complete inside the worker's 0.5 s disconnect-poll window and win
    # the race against the cancel)
    deadline = time.time() + 120
    while time.time() < deadline and t.is_alive():
        if any(_host_metrics(u)["engine"]["decode_tokens"]
               > tokens_before[u] for u in urls):
            break
        time.sleep(0.05)
    assert t.is_alive(), "victim finished before the cancel could land"
    router.cancel(77)
    t.join(timeout=120)
    assert not t.is_alive(), "cancelled request never returned"
    assert result["res"].finish_reason == "cancelled"
    # the abort reached the WORKER's scheduler (cross-process sweep)
    deadline = time.time() + 60
    while time.time() < deadline:
        cancelled_now = sum(
            _host_metrics(u)["engine"].get("cancelled", 0) for u in urls)
        if cancelled_now == cancelled_before + 1:
            break
        time.sleep(0.3)
    assert cancelled_now == cancelled_before + 1, \
        "worker never recorded the remote cancellation"


def test_streamed_cancel_is_cancelled_not_stop(cluster):
    """A cancel mid-SSE-stream must report finish_reason='cancelled' with
    only the deltas received — the server's unframed SSE body reads as a
    clean EOF on hangup, which must not masquerade as a normal 'stop'
    completion.  (Random-init workers flush deltas only at completion —
    invalid UTF-8 partials never form consistent prefixes — so the
    mid-decode trigger is the worker metrics poll, same as the
    non-streamed cancel test; the delta list is then typically empty.)"""
    urls, _, router = cluster
    deltas: list[str] = []
    result = {}

    def run() -> None:
        result["res"] = router.generate_batch(
            [GenerationRequest(prompt="stream cancel probe", request_id=5,
                               temperature=0.0, max_new_tokens=400)],
            on_tokens=lambda rid, piece: deltas.append(piece))[0]

    tokens_before = {u: _host_metrics(u)["engine"]["decode_tokens"]
                     for u in urls}
    t = threading.Thread(target=run)
    t.start()
    deadline = time.time() + 120
    while time.time() < deadline and t.is_alive():
        if any(_host_metrics(u)["engine"]["decode_tokens"]
               > tokens_before[u] for u in urls):
            break
        time.sleep(0.05)
    assert t.is_alive(), "victim finished before the cancel could land"
    router.cancel(5)
    t.join(timeout=120)
    assert not t.is_alive(), "cancelled streamed request never returned"
    res = result["res"]
    assert res.finish_reason == "cancelled", res
    assert res.text == "".join(deltas)
    assert res.completion_tokens < 400



def test_prefix_route_identity_on_jax_cluster(cluster):
    """The jax arm of the routing identity A/B: the same greedy
    same-preamble workload through the real two-scheduler cluster routed
    and round-robin — token-identical texts, and the routed arm reports
    prefix placements."""
    urls, _procs, _router = cluster
    hosts = [u.split("//", 1)[1] for u in urls]

    def run(prefix_route: bool) -> list[str]:
        router = RouterEngine(hosts, timeout_s=300.0,
                              prefix_route=prefix_route)
        try:
            out = []
            for w in range(3):  # single-request waves: RR scatters
                res = router.generate_batch([GenerationRequest(
                    prompt=_SHARED_PRE + "Chunk: facts here.",
                    request_id=w, temperature=0.0, max_new_tokens=12,
                    cache_prefix=len(_SHARED_PRE))])[0]
                assert res.error is None, res.error
                out.append(res.text)
            if prefix_route:
                em = router.engine_metrics()["prefix_route"]
                assert em["routed"] == 3, em
            return out
        finally:
            router.shutdown()

    routed = run(True)
    rr = run(False)
    assert routed == rr
    assert len(set(routed)) == 1  # same prompt, greedy: one text


def test_dead_host_degrades_not_fails(cluster):
    """Killing one worker mid-fleet must not fail the wave: requests
    reroute to the survivor and the dead host is marked unhealthy.
    (Runs LAST in this module — it takes a worker down.)"""
    urls, procs, router = cluster
    procs[1].kill()
    procs[1].wait(timeout=10)
    reqs = [GenerationRequest(prompt=f"survivor probe {i}", request_id=i,
                              temperature=0.0, max_new_tokens=4)
            for i in range(4)]
    out = router.generate_batch(reqs)
    assert all(r.error is None for r in out), [r.error for r in out]
    assert all(r.completion_tokens > 0 for r in out)
    m = router.engine_metrics()
    assert m["healthy_hosts"] == 1
    by_host = {row["host"]: row for row in m["per_host"]}
    dead = urls[1].removeprefix("http://")
    assert not by_host[dead]["healthy"]


def test_pipeline_map_reduce_over_http_fleet(tmp_path):
    """The COMPLETE map-reduce pipeline with backend='http': chunks fan
    over two lmrs-serve processes and the hierarchical reduce rides the
    same fleet — the reference's deployment shape (pipeline here, models
    behind HTTP there), with our servers on the far side."""
    import dataclasses

    from lmrs_tpu.config import EngineConfig, PipelineConfig
    from lmrs_tpu.pipeline import TranscriptSummarizer

    ports = [_free_port(), _free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    procs = [_spawn_mock_worker(p) for p in ports]
    try:
        for url, proc in zip(urls, procs):
            _wait_healthy(url, proc, deadline_s=60)
        segs, t = [], 0.0
        for i in range(400):
            segs.append({"start": t, "end": t + 2.0,
                         "text": f"Fleet pipeline segment {i} covers point {i % 13}.",
                         "speaker": "SPEAKER_00"})
            t += 2.2
        cfg = PipelineConfig(engine=EngineConfig(
            backend="http", hosts=tuple(urls), retry_delay=0.0))
        cfg = dataclasses.replace(
            cfg, chunk=dataclasses.replace(cfg.chunk, max_tokens_per_chunk=400))
        stats = TranscriptSummarizer(cfg).summarize({"segments": segs})
        assert stats["num_chunks"] >= 4
        assert stats["failed_requests"] == 0
        assert stats["summary"].strip()
        served = [_host_metrics(u)["http_requests"] for u in urls]
        assert all(n > 0 for n in served), f"fleet imbalance: {served}"
    finally:
        _teardown(procs)


def test_dead_host_recovers_via_probe(cluster):
    """A host that comes back (worker restart on the same port) must be
    re-admitted by the per-wave /healthz probe — an unhealthy mark is not
    a life sentence.  Runs after test_dead_host_degrades_not_fails killed
    worker 1; restarts it (mock backend: the router is engine-agnostic)."""
    urls, procs, router = cluster
    assert not router.hosts[1].healthy  # left dead by the previous test
    port = urls[1].rsplit(":", 1)[1]
    procs[1] = subprocess.Popen(
        [sys.executable, "-m", "lmrs_tpu.serving.cli",
         "--backend", "mock", "--port", port, "-q"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd="/root/repo",
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    _wait_healthy(urls[1], procs[1], deadline_s=60)
    # each wave launches probes at unhealthy hosts; a couple of waves give
    # the async probe time to land and the router starts routing there
    deadline = time.time() + 30
    while time.time() < deadline and not router.hosts[1].healthy:
        router.generate_batch(
            [GenerationRequest(prompt="probe tick", request_id=900,
                               temperature=0.0, max_new_tokens=2)])
        time.sleep(0.2)
    assert router.hosts[1].healthy, "probe never re-admitted the host"
    served_before = router.hosts[1].served
    out = router.generate_batch(
        [GenerationRequest(prompt=f"rejoin probe {i}", request_id=i,
                           temperature=0.0, max_new_tokens=2)
         for i in range(4)])
    assert all(r.error is None for r in out)
    assert router.hosts[1].served > served_before, \
        "re-admitted host received no traffic"


@pytest.mark.parametrize("seed", [7, 41])
def test_fuzzed_router_waves_with_cancels_and_kills(seed):
    """Router invariants under churn (SURVEY §5.2 for the multi-host
    tier): random waves with random mid-wave cancels and a mid-test
    worker kill — every request must get exactly ONE result (cancelled,
    completed, or error), ids and order preserved, and the router must
    never raise.  Mock-backend workers: the fuzz targets the ROUTING
    layer's state machine, not the engine (the scheduler has its own
    fuzz suite)."""
    rng = random.Random(seed)
    ports = [_free_port(), _free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    procs = [_spawn_mock_worker(p) for p in ports]
    router = RouterEngine(urls, timeout_s=60.0)
    try:
        for url, proc in zip(urls, procs):
            _wait_healthy(url, proc, deadline_s=60)
        rid = 0
        kill_wave = rng.randrange(2, 5)
        for wave in range(6):
            n = rng.randrange(1, 9)
            reqs = [GenerationRequest(prompt=f"fuzz {seed} {wave} {i}",
                                      request_id=rid + i, temperature=0.0,
                                      max_new_tokens=rng.randrange(1, 6))
                    for i in range(n)]
            rid += n
            victims = [r.request_id for r in reqs if rng.random() < 0.3]
            canceller = threading.Timer(
                0.001 * rng.randrange(0, 20),
                lambda v=victims: [router.cancel(x) for x in v])
            canceller.start()
            if wave == kill_wave:
                procs[1].kill()  # mid-fleet failure
            out = router.generate_batch(reqs)
            canceller.join()
            assert [r.request_id for r in out] == [r.request_id for r in reqs]
            for r in out:
                # mock waves are near-instant, so a cancel can land before,
                # during, or after its victim — any single coherent outcome
                # is legal, but exactly one result must exist per request
                assert r.finish_reason in ("stop", "length", "cancelled",
                                           "error"), r
            if wave == kill_wave:
                # restart so later waves can re-admit via the probe;
                # wait() first: SIGKILL returns before the kernel closes
                # the old listener, and a respawn would EADDRINUSE (same
                # reason the dead-host test reaps before asserting)
                procs[1].wait(timeout=10)
                procs[1] = _spawn_mock_worker(ports[1])
                _wait_healthy(urls[1], procs[1], deadline_s=60)
        # the fleet ends functional: one clean wave, no errors
        final = router.generate_batch(
            [GenerationRequest(prompt="post-fuzz", request_id=9999,
                               temperature=0.0, max_new_tokens=2)])
        assert final[0].error is None
    finally:
        router.shutdown()
        _teardown(procs)


# --------------------------------------------------- probe pacing (no fleet)


def test_probe_pacing_with_fake_clock():
    """A dead host under heavy traffic must not draw one /healthz probe per
    wave (a probe storm scaling with offered load): probes space at least
    probe_floor_s apart per host, plus jitter, enforced on an injectable
    clock so this test never sleeps."""
    clock = [100.0]
    router = RouterEngine(["127.0.0.1:1", "127.0.0.1:2"],
                          probe_floor_s=5.0, probe_jitter_s=2.0,
                          clock=lambda: clock[0])
    try:
        for h in router.hosts:
            h.healthy = False
            h.probe = lambda: False  # stays dead; no network touched
        assert len(router._launch_probes()) == 2  # both eligible at t=100
        # a storm of waves at the same instant: zero further probes
        for _ in range(50):
            assert router._launch_probes() == []
        clock[0] += 4.99  # just under the floor
        assert router._launch_probes() == []
        clock[0] += 5.0 + 2.0  # beyond floor + max jitter
        assert len(router._launch_probes()) == 2  # exactly one more each
        assert router._launch_probes() == []
        # a healthy host is never probed
        router.hosts[0].healthy = True
        clock[0] += 100.0
        assert router._launch_probes() == [router.hosts[1]]
    finally:
        router.shutdown()


# ------------------------------------------- fault-injection sites (no fleet)


def _mock_server():
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    srv = EngineHTTPServer(MockEngine(), port=0, batch_window_s=0.01)
    srv.start_background()
    return srv


def test_router_connect_fault_fails_over_and_marks_host():
    """An injected connection-phase fault must mark the first target
    unhealthy and fail the request over to the next host — the same path a
    dead backend takes, driven without killing a process."""
    from lmrs_tpu.testing import faults
    from lmrs_tpu.testing.faults import FaultPlan

    srv = _mock_server()
    url = f"127.0.0.1:{srv.port}"
    router = RouterEngine([url, url], timeout_s=30.0)  # same backend twice
    try:
        with faults.injected(FaultPlan(faults=[
                {"site": "router.connect", "at": [1], "max_fires": 1}])):
            res = router.generate_batch([GenerationRequest(
                prompt="failover probe", request_id=0)])[0]
        assert res.error is None  # the second target served it
        assert res.text
        fails = [h.failed for h in router.hosts]
        assert sorted(fails) == [0, 1], fails
        assert any(not h.healthy for h in router.hosts)  # condemned target
    finally:
        router.shutdown()
        srv.shutdown()


def test_router_recv_fault_surfaces_midstream_error():
    """An injected mid-stream fault AFTER deltas were forwarded must
    surface as an error result without a retry — a replay would duplicate
    the deltas already delivered (Engine streaming contract)."""
    from lmrs_tpu.testing import faults
    from lmrs_tpu.testing.faults import FaultPlan

    srv = _mock_server()
    router = RouterEngine([f"127.0.0.1:{srv.port}"], timeout_s=30.0)
    deltas: list[str] = []
    try:
        # SSE lines for the mock: role frame, blank, content frame, blank,
        # finish frame... — occurrence 5 lands after the content delta
        with faults.injected(FaultPlan(faults=[
                {"site": "router.recv", "at": [5], "max_fires": 1}])):
            res = router.generate_batch(
                [GenerationRequest(prompt="One fact. Two facts.",
                                   request_id=1)],
                on_tokens=lambda rid, d: deltas.append(d))[0]
        assert res.finish_reason == "error"
        assert deltas, "fault should land after the first content delta"
        assert router.hosts[0].healthy  # per-request fault, not a dead host
    finally:
        router.shutdown()
        srv.shutdown()


# ---------------------------------------------- prefix-aware routing (ISSUE 12)

_SHARED_PRE = ("You are summarizing one section of a much longer "
               "transcript. Keep every fact, decision, name, and number. ")


def _preamble_requests(lo: int, n: int) -> list[GenerationRequest]:
    return [GenerationRequest(
        prompt=_SHARED_PRE + f"Chunk {i}: the team discussed item {i}.",
        request_id=lo + i, temperature=0.0,
        system_prompt="Respond with the summary content only.",
        cache_prefix=len(_SHARED_PRE)) for i in range(n)]


def _mock_fleet(n: int = 2, **router_kw):
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    servers = [EngineHTTPServer(MockEngine(seed=0), port=0,
                                batch_window_s=0.01) for _ in range(n)]
    for s in servers:
        s.start_background()
    router = RouterEngine([f"127.0.0.1:{s.port}" for s in servers],
                          timeout_s=30.0, **router_kw)
    return servers, router


def test_request_body_forwards_cache_prefix():
    """The satellite regression (ISSUE 12): the wire must carry the
    prefix-cache hint end to end — _request_body emits it and the
    server's request builders parse it back — or routed requests insert
    uncapped into the backend radix tree."""
    from lmrs_tpu.serving.router import _request_body
    from lmrs_tpu.serving.server import (_chat_to_request,
                                         _messages_to_request)

    req = _preamble_requests(0, 1)[0]
    body = _request_body(req)
    assert body["cache_prefix"] == len(_SHARED_PRE)
    rebuilt = _chat_to_request(body, max_tokens_cap=4096)
    assert rebuilt.cache_prefix == len(_SHARED_PRE)
    assert rebuilt.prompt == req.prompt
    assert rebuilt.system_prompt == req.system_prompt
    body2 = dict(body, system=req.system_prompt)
    assert _messages_to_request(body2, 4096).cache_prefix == len(_SHARED_PRE)
    # hint-free requests forward no field and parse back None (and
    # garbage on the wire never crashes the builder)
    assert "cache_prefix" not in _request_body(
        GenerationRequest(prompt="p", request_id=1))
    assert _chat_to_request({"messages": [], "cache_prefix": True},
                            4096).cache_prefix is None


def test_routed_requests_hit_backend_prefix_cache():
    """Router→server regression: forwarded same-preamble requests REPORT
    prefix-cache hits on the backend (the hint actually reached the
    radix accounting), and prefix placement keeps them on ONE host."""
    servers, router = _mock_fleet(2)
    try:
        for w in range(5):  # single-request waves: RR would scatter
            res = router.generate_batch(_preamble_requests(w * 10, 1))[0]
            assert res.error is None
        per = [_host_metrics(f"http://127.0.0.1:{s.port}") for s in servers]
        blocks = [m["engine"].get("prefix_cache") for m in per
                  if m["engine"].get("prefix_cache")]
        assert len(blocks) == 1, "placement scattered across hosts"
        assert blocks[0]["queries"] == 5
        assert blocks[0]["hits"] == 4, blocks
        assert blocks[0]["prefill_tokens_saved"] > 0
        em = router.engine_metrics()["prefix_route"]
        assert em["enabled"] and em["routed"] == 5
    finally:
        router.shutdown()
        _shutdown_fleet(servers)


def _shutdown_fleet(servers) -> None:
    for s in servers:
        s.shutdown()


def test_prefix_route_identity_vs_round_robin():
    """Placement must never change outputs: the same workload through a
    routed fleet and a round-robin fleet produces identical texts (mock
    determinism is per (seed, prompt) — host-independent)."""
    servers, routed = _mock_fleet(2, summary_ttl_s=1.0)
    rr = RouterEngine([h.netloc for h in routed.hosts], timeout_s=30.0,
                      prefix_route=False)
    try:
        reqs = _preamble_requests(0, 6)
        t_routed = [r.text for r in routed.generate_batch(reqs)]
        t_rr = [r.text for r in rr.generate_batch(_preamble_requests(0, 6))]
        assert t_routed == t_rr
        assert all(t for t in t_routed)
        assert rr.engine_metrics()["prefix_route"]["enabled"] is False
        assert rr.engine_metrics()["prefix_route"]["routed"] == 0
    finally:
        routed.shutdown()
        rr.shutdown()
        _shutdown_fleet(servers)


def test_prefix_route_env_kill_switch(monkeypatch):
    monkeypatch.setenv("LMRS_PREFIX_ROUTE", "0")
    router = RouterEngine(["127.0.0.1:1"])
    try:
        assert router.prefix_route is False
        req = _preamble_requests(0, 1)[0]
        assert router._prefix_target(req) == (None, False, False)
    finally:
        router.shutdown()


def test_prefix_route_summary_predicted_placement():
    """With a short summary TTL the predicted path engages: the host that
    served the preamble publishes it via /healthz and later requests are
    placed on its summary, not just the rendezvous hash."""
    servers, router = _mock_fleet(2, summary_ttl_s=0.5)
    try:
        for i in range(3):
            router.generate_batch(_preamble_requests(i * 10, 1))
            time.sleep(0.4)  # let the wave-path summary refresh land
        em = router.engine_metrics()["prefix_route"]
        assert em["predicted"] >= 1, em
        assert em["routed"] == 3
    finally:
        router.shutdown()
        _shutdown_fleet(servers)


def test_prefix_route_ab_beats_round_robin_aggregate():
    """The acceptance A/B (ISSUE 12): over 2 hosts sharing preambles,
    routed placement raises the fleet-aggregate hit rate and
    prefill-tokens-saved vs round-robin (scripts/ab_prefix_route.py is
    the reporting harness; this is the tier-1 assertion)."""
    def run(prefix_route: bool) -> tuple[int, int]:
        servers, router = _mock_fleet(2, prefix_route=prefix_route)
        try:
            for w in range(6):
                res = router.generate_batch(
                    _preamble_requests(w * 10, 1))[0]
                assert res.error is None
            hits = saved = 0
            for s in servers:
                pc = _host_metrics(f"http://127.0.0.1:{s.port}")[
                    "engine"].get("prefix_cache") or {}
                hits += pc.get("hits", 0)
                saved += pc.get("prefill_tokens_saved", 0)
            return hits, saved
        finally:
            router.shutdown()
            _shutdown_fleet(servers)

    rr_hits, rr_saved = run(prefix_route=False)
    ro_hits, ro_saved = run(prefix_route=True)
    assert ro_hits > rr_hits, (ro_hits, rr_hits)
    assert ro_saved > rr_saved, (ro_saved, rr_saved)


def test_unhealthy_preferred_host_degrades_to_ordering():
    """A rendezvous/predicted pick that is unhealthy must degrade to the
    normal load/health order (the request still completes elsewhere)."""
    servers, router = _mock_fleet(2)
    try:
        req = _preamble_requests(0, 1)[0]
        prefer, _pred, eligible = router._prefix_target(req)
        assert eligible and prefer is not None
        prefer.healthy = False
        prefer2, _pred2, _el = router._prefix_target(req)
        assert prefer2 is not prefer
        res = router.generate_batch(_preamble_requests(0, 1))[0]
        assert res.error is None
        em = router.engine_metrics()["prefix_route"]
        assert em["routed"] >= 1
    finally:
        router.shutdown()
        _shutdown_fleet(servers)


def test_prefix_route_fair_share_keeps_fleet_busy():
    """A same-preamble BATCH wave must not serialize onto the sticky
    host: the wave planner caps the sticky share at ceil(group/healthy)
    and spreads the rest, so a map fan-out keeps every host busy while
    single-request waves stay fully sticky."""
    servers, router = _mock_fleet(2)
    try:
        out = router.generate_batch(_preamble_requests(0, 12))
        assert all(r.error is None for r in out)
        served = sorted(h.served for h in router.hosts)
        assert served[0] > 0, f"fleet imbalance: {served}"
        em = router.engine_metrics()["prefix_route"]
        # sticky share = ceil(12/2) = 6; the rest deliberately spread
        assert em["routed"] == 6 and em["fallback"] == 6, em
    finally:
        router.shutdown()
        _shutdown_fleet(servers)
