"""Subprocess entry point for the live-session SIGKILL chaos scenarios.

Runs ONE live session (SessionManager over a mock engine) inside its own
OS process so the parent test (tests/test_live.py) can SIGKILL it
mid-refresh by watching the write-ahead journal grow, then resume the
session in-process and assert the next refresh is token-identical to an
uninterrupted run with the clean subtrees never recomputed.

The parent paces the child's journal appends with a ``journal.append``
stall fault plan (LMRS_FAULT_PLAN in the child env) so the kill window
between records is wide and machine-speed independent.

The config builders below are the single source of truth for both
sides: the parent resumes under the SAME PipelineConfig, so the
session's config fingerprint matches and the journal rehydrates instead
of being set aside as stale.

Usage: ``python tests/_live_worker.py <spec.json>`` with
``{"live_dir", "session_id", "batches": [[segment...], ...]}``.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def live_segments(n: int = 60, seed: int = 2) -> list[dict]:
    """Deterministic synthetic live transcript (duplicated from the
    conftest schema so the child never imports the test harness)."""
    import random

    rng = random.Random(seed)
    words = ("the standup covered the live summarization tier session "
             "journal refresh cadence rolling reduce tree deadline "
             "classes and the router stickiness design").split()
    segs = []
    t = 0.0
    for i in range(n):
        dur = 3.0 + rng.random() * 5.0
        text = " ".join(rng.choice(words) for _ in range(10 + rng.randrange(12)))
        segs.append({"start": round(t, 2), "end": round(t + dur, 2),
                     "text": text.capitalize() + ".",
                     "speaker": f"SPEAKER_{i % 2:02d}"})
        t += dur + 0.5
    return segs


def live_pipeline_config():
    """The (chunk, engine, reduce, live) surface both sides run under:
    small chunks force a multi-chunk map, arity 3 forces a multi-level
    stable tree, so "mid-refresh" is a real kill window.  temperature=0
    end to end — the token-identity contract is greedy."""
    from lmrs_tpu.config import (ChunkConfig, EngineConfig, LiveConfig,
                                 PipelineConfig, ReduceConfig)

    return PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=120, overlap_tokens=0,
                          context_tokens=30, tokenizer="approx"),
        engine=EngineConfig(backend="mock", temperature=0.0, seed=0,
                            max_tokens=48, retry_delay=0.0),
        reduce=ReduceConfig(max_summaries_per_batch=3),
        live=LiveConfig(class_default="bulk"),
    )


def build_manager(live_dir: str):
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.live import SessionManager

    return SessionManager(MockEngine(seed=0), live_dir,
                          config=live_pipeline_config())


def main(spec_path: str) -> int:
    spec = json.loads(Path(spec_path).read_text(encoding="utf-8"))
    manager = build_manager(spec["live_dir"])
    sid = spec.get("session_id", "live")
    manager.create(session_id=sid)
    last = None
    for batch in spec["batches"]:
        doc = manager.append(sid, batch, refresh=True)
        last = doc.get("refresh")
    print(json.dumps({"session_id": sid,
                      "summary": (last or {}).get("summary")}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
