"""lmrs-train CLI (training/cli.py): data loading, masked fine-tune loop,
checkpoint output."""

import json

import numpy as np
import pytest

from lmrs_tpu.training.cli import batches, load_examples, main


class _Tok:
    bos_id, eos_id, pad_id = 1, 2, 0

    def encode(self, text):
        return [3 + (ord(c) % 60) for c in text]


def _write_data(path, n=6):
    rows = []
    for i in range(n):
        if i % 2:
            rows.append({"text": f"plain text example {i}"})
        else:
            rows.append({"prompt": f"summarize {i}:", "summary": f"sum {i}"})
    path.write_text("\n".join(json.dumps(r) for r in rows), encoding="utf-8")


def test_load_examples_masks(tmp_path):
    f = tmp_path / "d.jsonl"
    _write_data(f)
    seqs, masks = load_examples(str(f), _Tok())
    assert len(seqs) == 6
    # prompt/summary rows: mask 0 over prompt, 1 over summary+eos
    s0, m0 = seqs[0], masks[0]
    assert m0[0] == 0 and m0[-1] == 1
    assert s0[-1] == _Tok.eos_id
    # plain rows fully supervised
    assert all(masks[1])


def test_batches_shapes():
    seqs = [[1, 2, 3, 4], [1, 5, 6]]
    masks = [[1, 1, 1, 1], [1, 1, 1]]
    it = batches(seqs, masks, batch_size=2, seq_len=8, seed=0)
    t, m = next(it)
    assert t.shape == (2, 8) and m.shape == (2, 8)
    assert (t[:, 4:] == 0).all()


def test_batches_covers_tail():
    """Every epoch emits every example, including the non-divisible tail."""
    seqs = [[i + 1] for i in range(6)]
    masks = [[1]] * 6
    it = batches(seqs, masks, batch_size=4, seq_len=2, seed=0)
    seen = set()
    for _ in range(2):  # ceil(6/4) batches per epoch
        t, _ = next(it)
        seen.update(int(x) for x in t[:, 0])
    assert seen == {1, 2, 3, 4, 5, 6}


def test_train_cli_rejects_oov_tokenizer(tmp_path):
    """A tokenizer whose ids exceed the model vocab must fail fast, not
    silently clamp."""
    f = tmp_path / "d.jsonl"
    f.write_text(json.dumps({"text": "hello"}), encoding="utf-8")
    rc = main(["--data", str(f), "--model", "tiny", "--tokenizer", "approx",
               "--output", str(tmp_path / "o"), "--steps", "1", "-q"])
    assert rc == 1


def test_load_examples_rejects_malformed_row(tmp_path):
    f = tmp_path / "d.jsonl"
    f.write_text(json.dumps({"summary": "orphan"}), encoding="utf-8")
    with pytest.raises(ValueError, match="needs 'text'"):
        load_examples(str(f), _Tok())


def test_train_cli_end_to_end(tmp_path):
    f = tmp_path / "d.jsonl"
    _write_data(f, n=8)
    out = tmp_path / "ckpt"
    rc = main([
        "--data", str(f), "--model", "tiny", "--tokenizer", "byte",
        "--output", str(out), "--steps", "4", "--batch-size", "2",
        "--seq-len", "64", "--log-every", "2", "--remat", "-q",
    ])
    assert rc == 0
    assert out.exists()
    # checkpoint round-trips through the serving loader
    from lmrs_tpu.config import model_preset
    from lmrs_tpu.models.loader import load_checkpoint

    params = load_checkpoint(str(out), model_preset("tiny"))
    assert params["layers"]["attn"]["wq"].ndim == 4


def test_train_cli_mesh(tmp_path):
    f = tmp_path / "d.jsonl"
    _write_data(f, n=4)
    out = tmp_path / "ckpt"
    rc = main([
        "--data", str(f), "--model", "tiny", "--tokenizer", "byte",
        "--output", str(out), "--steps", "2", "--batch-size", "4",
        "--seq-len", "32", "--mesh", "2,2", "-q",
    ])
    assert rc == 0 and out.exists()


def test_train_cli_bad_data(tmp_path):
    rc = main(["--data", str(tmp_path / "missing.jsonl"), "--model", "tiny",
               "--output", str(tmp_path / "o"), "-q"])
    assert rc == 1
