"""Model-zoo unit tests (CPU, f32 for numerical checks)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import ModelConfig, model_preset
from lmrs_tpu.models.transformer import forward, init_kv_cache, init_params, param_count
from lmrs_tpu.ops.attention import attention
from lmrs_tpu.ops.norms import rms_norm
from lmrs_tpu.ops.rope import apply_rope, rope_table
from lmrs_tpu.ops.sampling import sample_logits


def tiny_cfg(**kw):
    d = dict(vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
             hidden_dim=64, max_seq_len=128, dtype="float32")
    d.update(kw)
    return ModelConfig(**d)


def test_param_shapes_and_count():
    cfg = tiny_cfg()
    p = init_params(cfg, jax.random.PRNGKey(0))
    assert p["embed"]["weight"].shape == (64, 32)
    assert p["layers"]["attn"]["wq"].shape == (2, 32, 4, 8)
    assert p["layers"]["mlp"]["w_down"].shape == (2, 64, 32)
    assert param_count(p) > 0


def test_forward_shapes():
    cfg = tiny_cfg()
    p = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.ones((3, 16), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(16)[None], (3, 16))
    logits, cache = forward(p, cfg, tokens, pos)
    assert logits.shape == (3, 16, 64)
    assert logits.dtype == jnp.float32
    assert cache is None


def test_causality():
    """Changing a future token must not change past logits."""
    cfg = tiny_cfg()
    p = init_params(cfg, jax.random.PRNGKey(1))
    key = jax.random.PRNGKey(2)
    t1 = jax.random.randint(key, (1, 12), 0, 64)
    t2 = t1.at[0, 8].set((t1[0, 8] + 1) % 64)
    pos = jnp.arange(12)[None]
    l1, _ = forward(p, cfg, t1, pos)
    l2, _ = forward(p, cfg, t2, pos)
    np.testing.assert_allclose(l1[0, :8], l2[0, :8], rtol=1e-5)
    assert not np.allclose(l1[0, 8:], l2[0, 8:])


def test_prefill_decode_equals_full_forward():
    """Prefill + stepwise decode through the KV cache must reproduce the
    no-cache forward logits (the correctness contract of the cache path)."""
    cfg = tiny_cfg()
    p = init_params(cfg, jax.random.PRNGKey(3))
    S = 10
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, S), 0, 64)
    pos = jnp.arange(S)[None]
    full_logits, _ = forward(p, cfg, tokens, pos)

    # prefill first 6, then decode 4 one-by-one
    cache = init_kv_cache(cfg, 1, S)
    pre = 6
    logits_p, cache = forward(p, cfg, tokens[:, :pre], pos[:, :pre], cache,
                              jnp.array([pre]))
    np.testing.assert_allclose(full_logits[:, :pre], logits_p, rtol=2e-4, atol=2e-5)
    for i in range(pre, S):
        li, cache = forward(p, cfg, tokens[:, i:i + 1], jnp.array([[i]]), cache,
                            jnp.array([i + 1]))
        np.testing.assert_allclose(full_logits[:, i], li[:, 0], rtol=2e-4, atol=2e-5)


def test_gqa_repeat_matches_mha_when_equal_heads():
    """attention with n_kv == n_heads is plain MHA; reference numerics check
    against an explicit softmax."""
    b, s, h, hd = 1, 5, 2, 4
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, s, h, hd))
    k = jax.random.normal(jax.random.fold_in(key, 1), (b, s, h, hd))
    v = jax.random.normal(jax.random.fold_in(key, 2), (b, s, h, hd))
    pos = jnp.arange(s)[None]
    out = attention(q, k, v, pos)
    # manual reference
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    mask = np.tril(np.ones((s, s), bool))
    logits = jnp.where(mask[None, None], logits, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1), v)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_rope_rotation_preserves_norm():
    sin, cos = rope_table(32, 8, 10000.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 2, 8))
    pos = jnp.arange(4)[None]
    y = apply_rope(x, pos, sin, cos)
    np.testing.assert_allclose(
        jnp.linalg.norm(x, axis=-1), jnp.linalg.norm(y, axis=-1), rtol=1e-5
    )
    # position 0 is identity
    np.testing.assert_allclose(x[:, 0], y[:, 0], rtol=1e-6)


def test_rms_norm_unit_scale():
    x = jnp.array([[3.0, 4.0]])
    out = rms_norm(x, jnp.zeros(2), eps=0.0)
    np.testing.assert_allclose(jnp.mean(out**2), 1.0, rtol=1e-5)


def test_sampling_greedy_and_temperature():
    logits = jnp.array([[0.0, 5.0, 1.0], [9.0, 0.0, 0.0]])
    key = jax.random.PRNGKey(0)
    ids = sample_logits(logits, key, jnp.zeros(2), jnp.zeros(2, jnp.int32), jnp.ones(2))
    assert ids.tolist() == [1, 0]
    # top_k=1 forces argmax even at high temperature
    ids = sample_logits(logits, key, jnp.full((2,), 5.0), jnp.ones(2, jnp.int32), jnp.ones(2))
    assert ids.tolist() == [1, 0]


def test_sampling_top_p_restricts_support():
    # one dominant token (p≈0.95): top_p=0.5 must always pick it
    logits = jnp.array([[6.0, 0.0, 0.0, 0.0]])
    for i in range(5):
        ids = sample_logits(logits, jax.random.PRNGKey(i), jnp.ones(1),
                            jnp.zeros(1, jnp.int32), jnp.array([0.5]))
        assert ids[0] == 0


def test_sampling_temp_only_matches_filtered_formulation():
    # temperature>0 with top_k=0/top_p=1 takes the sort-free fast branch
    # (the lax.cond added in round 5); it must draw the SAME token as the
    # filter_logits formulation — with every mask disabled the filtered
    # logits ARE the scaled logits, so the same key over the same
    # distribution is the equivalence the fast path's docstring claims.
    from lmrs_tpu.ops.sampling import filter_logits

    logits = jnp.asarray(
        jax.random.normal(jax.random.PRNGKey(7), (3, 64)) * 3.0)
    temps = jnp.array([0.3, 1.7, 0.0])
    tk = jnp.zeros(3, jnp.int32)
    tp = jnp.ones(3)
    for i in range(5):
        key = jax.random.PRNGKey(i)
        fast = sample_logits(logits, key, temps, tk, tp)
        masked = filter_logits(logits, temps, tk, tp)
        slow = jax.random.categorical(key, masked, axis=-1)
        want = jnp.where(temps > 0, slow, jnp.argmax(logits, -1))
        assert fast.tolist() == want.tolist()


def test_sampler_cond_survives_scheduler_contexts():
    """Guard for the round-5 lax.cond fast paths (ADVICE r5): the full-
    vocab sort and the categorical draw are gated by TWO lax.conds that
    must survive the jit contexts the engines actually call from — a
    ``lax.scan`` decode block (continuous scheduler) and a
    ``lax.while_loop`` body (static engine).  Under ``vmap`` over batched
    sampler params those conds silently lower to compute-both-branches
    (the sort runs for every row mix) — asserted here as the degenerate
    so the guard fails loudly if anyone ever routes sampling through
    vmap.  The jaxpr is the contract: 'cond' surviving tracing is exactly
    'the sort is device-branched', no timing flakiness."""
    logits = jnp.zeros((4, 32))
    key = jax.random.PRNGKey(0)
    temps = jnp.zeros((4,))
    tk = jnp.zeros((4,), jnp.int32)
    tp = jnp.ones((4,))

    def scan_block(logits, key, temps, tk, tp):
        # the scheduler's decode-block shape: sample_logits per scan step
        def step(carry, _):
            key, sub = jax.random.split(carry)
            return key, sample_logits(logits, sub, temps, tk, tp)

        return jax.lax.scan(step, key, None, length=4)

    def while_block(logits, key, temps, tk, tp):
        # the static engine's while_loop shape (jax_engine._get_gen_fn)
        def cond(state):
            return state[0] < 2

        def body(state):
            i, key, _ = state
            key, sub = jax.random.split(key)
            return i + 1, key, sample_logits(logits, sub, temps, tk, tp)

        return jax.lax.while_loop(
            cond, body, (0, key, jnp.zeros((4,), jnp.int32)))

    for ctx in (scan_block, while_block):
        jaxpr = str(jax.make_jaxpr(ctx)(logits, key, temps, tk, tp))
        assert jaxpr.count("cond[") >= 2, (
            f"{ctx.__name__}: sampler lax.cond gates did not survive "
            "tracing — the 4.8 ms/step full-vocab sort would run "
            "unconditionally (docs/PERF.md round 5)")

    # the documented degradation is real: vmap over batched sampler
    # params batches the predicate and the conds vanish
    vmapped = jax.vmap(
        lambda l, t: sample_logits(l[None], key, t[None], tk[:1], tp[:1])[0])
    jaxpr = str(jax.make_jaxpr(vmapped)(logits, temps))
    assert "cond[" not in jaxpr, (
        "vmap no longer degrades the cond gates — the call-site comments "
        "(scheduler/jax_engine) and ops/sampling.py NOTE can be relaxed")


def test_model_presets_exist():
    for name in ["tiny", "llama3-8b", "llama3-70b", "gemma-2b", "gemma-7b"]:
        cfg = model_preset(name)
        assert cfg.dim % cfg.n_heads == 0
    with pytest.raises(ValueError):
        model_preset("nope")


def test_gemma_quirks_apply():
    cfg = tiny_cfg(embed_scale=True, logit_softcap=5.0)
    p = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.ones((1, 4), jnp.int32)
    pos = jnp.arange(4)[None]
    logits, _ = forward(p, cfg, tokens, pos)
    assert float(jnp.max(jnp.abs(logits))) <= 5.0 + 1e-4
