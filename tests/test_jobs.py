"""Durable summarization jobs: WAL journal, crash-safe resume, async API.

The job-durability contract (docs/ROBUSTNESS.md § Durable jobs):

* the journal is CRC-framed, fsync'd, torn-tail tolerant, and its replay
  is idempotent — the same journal replayed any number of times yields
  byte-identical job state;
* a job resumes at the exact unit of work it died at: journaled chunk
  summaries rehydrate instead of recomputing, journaled reduce-tree
  nodes answer their content-addressed keys instead of re-running, and
  the resumed greedy final summary is token-identical to an
  uninterrupted run;
* the serving tier exposes it as POST/GET/DELETE /v1/jobs, surviving a
  server restart (SIGKILL'd server process included), with router
  forwarding for fleet deployments;
* journal I/O faults DEGRADE durability, never the job; a recovery fault
  degrades per job, never the startup.

The SIGKILL-mid-map / mid-reduce / torn-tail / duplicate-replay chaos
scenarios live in tests/test_chaos.py (the tier-1 chaos gate); this file
owns the journal units, manager semantics, and the HTTP surface.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

sys.path.insert(0, os.path.dirname(__file__))
import _job_worker as jw  # noqa: E402 - shared parent/child job configs
from conftest import free_port  # noqa: E402

from lmrs_tpu.config import JobsConfig, PipelineConfig  # noqa: E402
from lmrs_tpu.engine.mock import MockEngine  # noqa: E402
from lmrs_tpu.jobs import journal as jl  # noqa: E402
from lmrs_tpu.jobs.manager import JobManager  # noqa: E402
from lmrs_tpu.testing import faults  # noqa: E402
from lmrs_tpu.testing.faults import FaultPlan  # noqa: E402


# ------------------------------------------------------------ journal units


def test_journal_roundtrip(tmp_path):
    j = jl.Journal(tmp_path / "a.wal")
    recs = [{"type": "job_header", "job_id": "j1", "fingerprint": "f"},
            {"type": "chunk_done", "chunk_index": 0, "start_time": 0.0,
             "end_time": 1.0, "summary": "s0", "error": None},
            {"type": "job_done", "status": "done"}]
    for r in recs:
        assert j.append(r) is True
    j.close()
    out, meta = jl.replay(tmp_path / "a.wal")
    assert out == recs
    assert meta == {"records": 3, "dropped": 0, "torn": False,
                    "corrupt": False}
    assert j.stats() == {"appends": 3, "append_failures": 0,
                         "fsync_failures": 0, "degraded": False}


def test_journal_torn_tail_tolerated(tmp_path):
    """A crash mid-append leaves a partial final line: replay drops it
    (meta['torn']) and keeps everything before it."""
    p = tmp_path / "t.wal"
    j = jl.Journal(p)
    j.append({"type": "chunk_done", "chunk_index": 0, "start_time": 0.0,
              "end_time": 1.0, "summary": "s"})
    j.append({"type": "chunk_done", "chunk_index": 1, "start_time": 1.0,
              "end_time": 2.0, "summary": "t"})
    j.close()
    with open(p, "ab") as fh:  # torn: half a frame, no newline
        fh.write(b'deadbeef {"type":"chunk_do')
    out, meta = jl.replay(p)
    assert len(out) == 2 and out[1]["summary"] == "t"
    assert meta["torn"] is True and meta["dropped"] == 1
    assert meta["corrupt"] is False


def test_journal_midfile_corruption_drops_suffix(tmp_path):
    """Damage BEFORE the tail is not a torn append — everything after the
    bad record is untrusted and dropped."""
    p = tmp_path / "c.wal"
    j = jl.Journal(p)
    for i in range(4):
        j.append({"type": "chunk_done", "chunk_index": i, "start_time": 0.0,
                  "end_time": 1.0, "summary": f"s{i}"})
    j.close()
    lines = p.read_bytes().split(b"\n")
    lines[1] = lines[1][:12] + b"X" + lines[1][13:]  # flip a payload byte
    p.write_bytes(b"\n".join(lines))
    out, meta = jl.replay(p)
    assert [r["chunk_index"] for r in out] == [0]
    assert meta["corrupt"] is True and meta["dropped"] == 3
    assert meta["torn"] is False


def test_journal_replay_determinism_and_duplicate_idempotence(tmp_path):
    """Satellite: the same journal replayed twice yields byte-identical
    state, and duplicated records (a crash window re-appending) change
    nothing — rebuild keys by content identity."""
    p = tmp_path / "d.wal"
    j = jl.Journal(p)
    recs = [{"type": "job_header", "job_id": "j", "fingerprint": "f"},
            {"type": "chunk_done", "chunk_index": 0, "start_time": 0.0,
             "end_time": 1.5, "summary": "alpha"},
            {"type": "reduce_node_done", "node_id": "L1.B0", "key": "k0",
             "text": "node"}]
    for r in recs:
        j.append(r)
    once = jl.canonical_json(jl.rebuild_state(jl.replay(p)[0]))
    twice = jl.canonical_json(jl.rebuild_state(jl.replay(p)[0]))
    assert once == twice  # byte-identical replay
    for r in recs:  # duplicate every record (idempotent rebuild)
        j.append(r)
    j.close()
    doubled = jl.canonical_json(jl.rebuild_state(jl.replay(p)[0]))
    assert doubled == once


def test_journal_unknown_record_types_ignored(tmp_path):
    p = tmp_path / "u.wal"
    j = jl.Journal(p)
    j.append({"type": "job_header", "job_id": "j", "fingerprint": "f"})
    j.append({"type": "from_the_future", "payload": 1})
    j.close()
    state = jl.rebuild_state(jl.replay(p)[0])
    assert state["header"] is not None
    assert state["chunks"] == {} and state["nodes"] == {}


def test_journal_append_and_fsync_faults_degrade(tmp_path):
    """journal.append / journal.fsync fault sites: the append reports
    non-durable (False) and flags degradation, but never raises — journal
    I/O failure must not kill the job whose progress it records."""
    j = jl.Journal(tmp_path / "f.wal")
    with faults.injected(FaultPlan(faults=[
            {"site": "journal.append", "at": [2], "max_fires": 1},
            {"site": "journal.fsync", "at": [2], "max_fires": 1}])):
        assert j.append({"type": "chunk_done", "chunk_index": 0,
                         "start_time": 0.0, "end_time": 1.0}) is True
        # occurrence 2: the append itself fails — record dropped
        assert j.append({"type": "chunk_done", "chunk_index": 1,
                         "start_time": 0.0, "end_time": 1.0}) is False
        # append occurrence 3 lands, fsync occurrence 2 fails — written
        # but not durable
        assert j.append({"type": "chunk_done", "chunk_index": 2,
                         "start_time": 0.0, "end_time": 1.0}) is False
        assert j.append({"type": "chunk_done", "chunk_index": 3,
                         "start_time": 0.0, "end_time": 1.0}) is True
    j.close()
    s = j.stats()
    assert s["degraded"] and s["append_failures"] == 1
    assert s["fsync_failures"] == 1
    out, _ = jl.replay(j.path)
    assert [r["chunk_index"] for r in out] == [0, 2, 3]


def test_content_addressing():
    """Job ids key on (transcript, fingerprint); fingerprints key on the
    prompt/model surface — a different map prompt is a DIFFERENT job."""
    t1 = {"segments": [{"start": 0, "end": 1, "text": "a"}]}
    t2 = {"segments": [{"start": 0, "end": 1, "text": "b"}]}
    fa = jl.config_fingerprint(map_prompt="A", model="m")
    fb = jl.config_fingerprint(map_prompt="B", model="m")
    assert fa != fb
    assert jl.job_id_for(t1, fa) == jl.job_id_for(t1, fa)
    assert jl.job_id_for(t1, fa) != jl.job_id_for(t2, fa)
    assert jl.job_id_for(t1, fa) != jl.job_id_for(t1, fb)
    # node keys: content-addressed on exactly the inputs that shape the
    # prompt
    assert jl.node_key(["s1", "s2"], "T", {"m": 1}) == \
        jl.node_key(["s1", "s2"], "T", {"m": 1})
    assert jl.node_key(["s1", "s2"], "T", {"m": 1}) != \
        jl.node_key(["s2", "s1"], "T", {"m": 1})


# -------------------------------------------------------- manager semantics


@pytest.fixture(scope="module")
def baseline(tmp_path_factory):
    """One uninterrupted mock job: the token-identity reference every
    resume test compares against, plus its WAL for prefix surgery."""
    d = tmp_path_factory.mktemp("jobs_baseline")
    jm = JobManager(jw.build_engine("mock"), d,
                    config=jw.job_pipeline_config("mock"),
                    start_worker=False)
    job = jm.submit(jw.job_transcript())
    jm.run_job(job)
    assert job.status == "done" and job.n_chunks >= 5
    assert job.reduce_nodes_done >= 3  # hierarchical: mid-reduce is real
    lines = job.wal_path.read_bytes().split(b"\n")[:-1]
    jm.shutdown()
    return {"dir": d, "jid": job.job_id, "summary": job.result["summary"],
            "n_chunks": job.n_chunks, "lines": lines,
            "result": job.result}


def _wal_lines_by_type(lines: list[bytes]) -> dict[str, list[bytes]]:
    by_type: dict[str, list[bytes]] = {}
    for raw in lines:
        rec = json.loads(raw[9:])
        by_type.setdefault(rec["type"], []).append(raw)
    return by_type


def _interrupted_dir(baseline, tmp_path, n_chunks: int,
                     n_nodes: int = 0) -> Path:
    """A jobs dir that looks exactly like a crash left it: the request
    file plus a WAL prefix (header, the first n_chunks chunk records, the
    first n_nodes reduce records) — no terminal record."""
    by = _wal_lines_by_type(baseline["lines"])
    keep = (by["job_header"] + by["chunk_done"][:n_chunks]
            + by["reduce_node_done"][:n_nodes])
    d = tmp_path / "resume"
    d.mkdir()
    jid = baseline["jid"]
    (d / f"{jid}.req.json").write_bytes(
        (baseline["dir"] / f"{jid}.req.json").read_bytes())
    (d / f"{jid}.wal").write_bytes(b"\n".join(keep) + b"\n")
    return d


def test_resume_mid_map_token_identical(baseline, tmp_path):
    """Crash after 3 journaled chunk summaries: recovery re-queues the
    job, the 3 chunks rehydrate (not recompute), and the final summary is
    token-identical to the uninterrupted run."""
    d = _interrupted_dir(baseline, tmp_path, n_chunks=3)
    jm = JobManager(jw.build_engine("mock"), d,
                    config=jw.job_pipeline_config("mock"),
                    start_worker=False)
    assert jm.recover() == 1
    job = jm.get(baseline["jid"])
    assert job.status == "queued" and job.recovered
    jm.run_job(job)
    assert job.status == "done"
    assert job.resumed_chunks == 3
    assert job.result["num_resumed_chunks"] == 3
    assert job.result["summary"] == baseline["summary"]
    # the map stage really skipped the journaled chunks
    assert job.result["total_requests"] < baseline["result"]["total_requests"]
    jm.shutdown()


def test_resume_mid_reduce_reuses_exact_tree_nodes(baseline, tmp_path):
    """Crash mid-reduce: every chunk and the first 3 reduce nodes are
    journaled.  The resumed run answers those nodes from the journal
    (content-addressed keys — the exact-tree-node resume contract) and
    recomputes only the rest, landing on the identical final summary."""
    d = _interrupted_dir(baseline, tmp_path,
                        n_chunks=baseline["n_chunks"], n_nodes=3)
    jm = JobManager(jw.build_engine("mock"), d,
                    config=jw.job_pipeline_config("mock"),
                    start_worker=False)
    assert jm.recover() == 1
    job = jm.get(baseline["jid"])
    jm.run_job(job)
    assert job.status == "done"
    assert job.resumed_chunks == baseline["n_chunks"]
    assert job.reduce_nodes_reused == 3
    assert job.result["summary"] == baseline["summary"]
    # only the un-journaled reduce nodes hit the engine
    assert (job.result["total_requests"]
            == baseline["result"]["reduce_levels"] * 0
            + len(_wal_lines_by_type(baseline["lines"])["reduce_node_done"])
            - 3)
    jm.shutdown()


def test_resume_duplicate_replay_no_recompute(baseline, tmp_path):
    """Every record journaled twice (crash-window re-append): the rebuild
    is idempotent, so the resumed run rehydrates everything exactly once
    and issues ZERO engine requests — and still reports the identical
    summary."""
    by = _wal_lines_by_type(baseline["lines"])
    work = by["job_header"] + by["chunk_done"] + by["reduce_node_done"]
    d = tmp_path / "dup"
    d.mkdir()
    jid = baseline["jid"]
    (d / f"{jid}.req.json").write_bytes(
        (baseline["dir"] / f"{jid}.req.json").read_bytes())
    (d / f"{jid}.wal").write_bytes(b"\n".join(work + work) + b"\n")
    jm = JobManager(jw.build_engine("mock"), d,
                    config=jw.job_pipeline_config("mock"),
                    start_worker=False)
    assert jm.recover() == 1
    job = jm.get(jid)
    jm.run_job(job)
    assert job.status == "done"
    assert job.result["summary"] == baseline["summary"]
    assert job.result["total_requests"] == 0  # nothing recomputed
    jm.shutdown()


def test_recover_fingerprint_mismatch_sets_journal_aside(baseline, tmp_path):
    """Satellite contract at the job tier: a journal written under a
    different prompt/model surface must NOT rehydrate.  Restarting under a
    changed config recomputes the fingerprint, the gate fires, the stale
    WAL is set aside, and the job re-runs from scratch."""
    d = _interrupted_dir(baseline, tmp_path, n_chunks=3)
    cfg = jw.job_pipeline_config("mock")
    cfg = cfg.replace(engine=type(cfg.engine)(
        backend="mock", temperature=0.0, seed=0, max_tokens=47,
        retry_delay=0.0))  # max_tokens differs -> different fingerprint
    jm = JobManager(jw.build_engine("mock"), d, config=cfg,
                    start_worker=False)
    assert jm.recover() == 1
    job = jm.get(baseline["jid"])
    jm.run_job(job)
    assert job.status == "done"
    assert job.resumed_chunks == 0  # nothing rehydrated
    assert (d / f"{baseline['jid']}.wal.stale").exists()
    # the fresh journal carries the NEW fingerprint
    state = jl.rebuild_state(jl.replay(job.wal_path)[0])
    assert state["header"]["fingerprint"] == job.fingerprint
    jm.shutdown()


def test_duplicate_submit_converges(tmp_path):
    """Content-addressed submits: the same (transcript, params) twice is
    ONE job; a different params surface is another."""
    jm = JobManager(jw.build_engine("mock"), tmp_path,
                    config=jw.job_pipeline_config("mock"),
                    start_worker=False)
    t = jw.job_transcript(n=8)
    a = jm.submit(t)
    b = jm.submit(t)
    assert a is b
    c = jm.submit(t, {"summary_type": "minutes"})
    assert c.job_id != a.job_id
    with pytest.raises(ValueError, match="unknown job param"):
        jm.submit(t, {"tempreature": 1.0})
    jm.shutdown()


def test_resubmit_after_failure_retries_on_same_journal(tmp_path):
    """A failed job's terminal record must not block an explicit retry:
    resubmitting re-queues on the SAME journal, supersedes the stale
    job_done, and the retry resumes whatever succeeded before."""
    t = jw.job_transcript(n=8)
    jm = JobManager(MockEngine(seed=0, fail_pattern="roadmap"), tmp_path,
                    config=jw.job_pipeline_config("mock"),
                    jobs_config=JobsConfig(max_failed_chunk_fraction=0.0),
                    start_worker=False)
    job = jm.submit(t)
    jm.run_job(job)
    assert job.status == "failed" and job.chunks_failed > 0
    ok_chunks = job.chunks_done - job.chunks_failed
    # retry on an engine that no longer fails
    jm2 = JobManager(jw.build_engine("mock"), tmp_path,
                     config=jw.job_pipeline_config("mock"),
                     start_worker=False)
    assert jm2.recover() == 0  # failed is terminal at startup
    retry = jm2.submit(t)
    assert retry.status == "queued"  # explicit resubmit = retry
    jm2.run_job(retry)
    assert retry.status == "done"
    assert retry.resumed_chunks == ok_chunks  # successes rehydrated
    assert retry.error is None and retry.result["summary"]
    # the superseding terminal record wins on the next restart
    jm3 = JobManager(jw.build_engine("mock"), tmp_path,
                     config=jw.job_pipeline_config("mock"),
                     start_worker=False)
    jm3.recover()
    assert jm3.get(retry.job_id).status == "done"
    for m in (jm, jm2, jm3):
        m.shutdown()


def _marked_transcript() -> dict:
    """jw.job_transcript with ONE segment carrying the mock fail marker —
    lands in exactly one chunk (the degraded-threshold scenarios need a
    failed-chunk fraction of exactly 1/n_chunks)."""
    t = jw.job_transcript()
    t["segments"][2]["text"] = "This segment says XXFAILXX loudly."
    return t


def test_degraded_completion_under_threshold(tmp_path):
    """Satellite: failed-chunk fraction within policy finishes
    status='degraded' with per-chunk degraded_reasons attached — not
    all-or-nothing failure."""
    cfg = jw.job_pipeline_config("mock")
    cfg = cfg.replace(chunk=type(cfg.chunk)(
        max_tokens_per_chunk=150, overlap_tokens=0, context_tokens=0))
    jm = JobManager(MockEngine(seed=0, fail_pattern="XXFAILXX"), tmp_path,
                    config=cfg,
                    jobs_config=JobsConfig(max_failed_chunk_fraction=0.34),
                    start_worker=False)
    job = jm.submit(_marked_transcript())
    jm.run_job(job)
    assert job.status == "degraded"
    assert job.chunks_failed == 1
    assert len(job.degraded_reasons) == 1
    assert "injected failure" in job.degraded_reasons[0]["degraded_reason"]
    assert job.result["summary"]  # degrade-and-continue output attached
    doc = jm.status_doc(job)
    assert doc["status"] == "degraded" and doc["degraded_reasons"]
    # the degraded terminal state survives a restart
    jm2 = JobManager(jw.build_engine("mock"), tmp_path, config=cfg,
                     start_worker=False)
    assert jm2.recover() == 0
    assert jm2.get(job.job_id).status == "degraded"
    jm.shutdown(), jm2.shutdown()


def test_degraded_completion_over_threshold_fails(tmp_path):
    """The other side of the policy line: the same single failed chunk
    with a zero-tolerance threshold is a FAILED job (reasons still
    attached for triage)."""
    cfg = jw.job_pipeline_config("mock")
    cfg = cfg.replace(chunk=type(cfg.chunk)(
        max_tokens_per_chunk=150, overlap_tokens=0, context_tokens=0))
    jm = JobManager(MockEngine(seed=0, fail_pattern="XXFAILXX"), tmp_path,
                    config=cfg,
                    jobs_config=JobsConfig(max_failed_chunk_fraction=0.0),
                    start_worker=False)
    job = jm.submit(_marked_transcript())
    jm.run_job(job)
    assert job.status == "failed"
    assert job.chunks_failed == 1 and job.degraded_reasons
    jm.shutdown()


def test_jobs_config_validates_fraction():
    with pytest.raises(ValueError, match="max_failed_chunk_fraction"):
        JobsConfig(max_failed_chunk_fraction=1.5)


def test_cancel_running_job_then_retry(tmp_path):
    """DELETE semantics: a running job cancels (journaled terminal), its
    in-flight chunks are chased through the executor's cancel hooks; a
    later resubmit retries on the same journal."""
    t = jw.job_transcript()
    jm = JobManager(MockEngine(seed=0, latency_s=0.15), tmp_path,
                    config=jw.job_pipeline_config("mock"))  # real worker
    job = jm.submit(t)
    deadline = time.time() + 30
    while job.status != "running" and time.time() < deadline:
        time.sleep(0.01)
    assert job.status == "running"
    jm.cancel(job.job_id)
    assert job.done_ev.wait(30)
    assert job.status == "cancelled"
    state = jl.rebuild_state(jl.replay(job.wal_path)[0])
    assert state["done"]["status"] == "cancelled"  # survives restart
    jm.shutdown()
    # retry: instantaneous engine, same journal
    jm2 = JobManager(jw.build_engine("mock"), tmp_path,
                     config=jw.job_pipeline_config("mock"),
                     start_worker=False)
    assert jm2.recover() == 0  # cancelled is terminal at startup
    retry = jm2.submit(t)
    jm2.run_job(retry)
    assert retry.status == "done" and retry.result["summary"]
    jm2.shutdown()


def test_recover_fault_degrades_per_job(baseline, tmp_path):
    """jobs.recover fault site: the faulted job is registered failed (the
    interruption stays visible), the OTHER interrupted job still
    recovers and completes."""
    d = _interrupted_dir(baseline, tmp_path, n_chunks=2)
    # a second interrupted job: different transcript, fresh journal
    jm0 = JobManager(jw.build_engine("mock"), d,
                     config=jw.job_pipeline_config("mock"),
                     start_worker=False)
    other = jm0.submit(jw.job_transcript(n=8, seed=5))
    jm0.shutdown()  # header journaled, never run -> interrupted
    with faults.injected(FaultPlan(faults=[
            {"site": "jobs.recover", "at": [1], "max_fires": 1}])):
        jm = JobManager(jw.build_engine("mock"), d,
                        config=jw.job_pipeline_config("mock"),
                        start_worker=False)
        assert jm.recover() == 1  # one failed, one re-queued
    statuses = {j.job_id: j.status for j in jm.jobs()}
    assert sorted(statuses.values()) == ["failed", "queued"]
    failed_id = next(k for k, v in statuses.items() if v == "failed")
    assert "recovery failed" in jm.get(failed_id).error
    runnable = jm.get(next(k for k, v in statuses.items() if v == "queued"))
    jm.run_job(runnable)
    assert runnable.status == "done"
    assert other.job_id in statuses
    jm.shutdown()


def test_journal_append_after_partial_tail_repairs(tmp_path):
    """Appending over a file that ends mid-line (a torn tail, or bytes a
    failed append left behind) must not merge two records into one
    corrupt mid-file line — that would make replay drop every record
    AFTER it, records already acknowledged durable.  The (re)open
    truncates the partial tail first."""
    wal = tmp_path / "x.wal"
    j = jl.Journal(wal)
    assert j.append({"type": "chunk_done", "chunk_index": 1})
    j.close()
    with open(wal, "ab") as fh:
        fh.write(b'deadbeef {"type":"chunk_done","chunk_in')  # no newline
    j2 = jl.Journal(wal)
    assert j2.append({"type": "chunk_done", "chunk_index": 2})
    j2.close()
    recs, meta = jl.replay(wal)
    assert meta["corrupt"] is False and meta["torn"] is False
    assert [r["chunk_index"] for r in recs] == [1, 2]


def test_resubmit_queued_job_supersedes_pending_cancel(tmp_path):
    """DELETE on a QUEUED job then an identical re-POST: the resubmit is
    acknowledged "queued" and must actually run — the pending cancel is
    superseded, not silently honored at dequeue."""
    jm = JobManager(jw.build_engine("mock"), tmp_path,
                    config=jw.job_pipeline_config("mock"),
                    start_worker=False)
    t = jw.job_transcript(n=8)
    job = jm.submit(t)
    jm.cancel(job.job_id)
    assert job.status == "queued" and job.cancel_ev.is_set()
    again = jm.submit(t)
    assert again is job and not job.cancel_ev.is_set()
    jm.run_job(job)
    assert job.status == "done"
    jm.shutdown()


def test_resubmit_running_job_mid_cancel_requeues(tmp_path):
    """The same race against a RUNNING job: DELETE starts the unwind,
    an identical POST lands before the cancelled finish — the job must
    re-queue and run to completion once the cancel lands, not leave the
    acknowledged submit swallowed."""
    t = jw.job_transcript()
    jm = JobManager(MockEngine(seed=0, latency_s=0.15), tmp_path,
                    config=jw.job_pipeline_config("mock"))  # real worker
    job = jm.submit(t)
    deadline = time.time() + 30
    while job.status != "running" and time.time() < deadline:
        time.sleep(0.01)
    assert job.status == "running"
    jm.cancel(job.job_id)
    again = jm.submit(t)
    assert again is job and job.resubmit_pending
    deadline = time.time() + 60
    while job.status != "done" and time.time() < deadline:
        time.sleep(0.02)
    assert job.status == "done" and job.result["summary"]
    jm.shutdown()


def test_resubmit_after_failed_recovery_heals(baseline, tmp_path):
    """A job registered by a FAILED recovery carries params={} and
    fingerprint=""; an explicit resubmit with the real (transcript,
    params) must heal both — re-queueing on the SAME journal instead of
    running default params and stale-siding its own progress."""
    d = _interrupted_dir(baseline, tmp_path, n_chunks=3)
    with faults.injected(FaultPlan(faults=[
            {"site": "jobs.recover", "at": [1], "max_fires": 1}])):
        jm = JobManager(jw.build_engine("mock"), d,
                        config=jw.job_pipeline_config("mock"),
                        start_worker=False)
        assert jm.recover() == 0
    job = jm.get(baseline["jid"])
    assert job.status == "failed" and job.fingerprint == ""
    retry = jm.submit(jw.job_transcript())
    assert retry is job and retry.status == "queued"
    assert retry.fingerprint != ""
    jm.run_job(retry)
    assert retry.status == "done"
    assert retry.resumed_chunks == 3  # the journal was NOT stale-sided
    assert retry.result["summary"] == baseline["summary"]
    assert not Path(str(job.wal_path) + ".stale").exists()
    jm.shutdown()


def test_reduce_error_final_marker_fails_job(tmp_path):
    """Every reduce node degrading to an error marker must not journal a
    terminal "done" around a garbage summary: the job is FAILED (and
    therefore retryable), with the reduce degradation in the reasons."""
    jm = JobManager(MockEngine(seed=0, fail_pattern="SUMMARY 1:"), tmp_path,
                    config=jw.job_pipeline_config("mock"),
                    start_worker=False)
    job = jm.submit(jw.job_transcript())
    jm.run_job(job)
    assert job.status == "failed"
    assert job.chunks_failed == 0  # the map was clean; the REDUCE broke
    assert job.result["reduce_errors"] >= 1
    assert any(r.get("node") == "reduce" for r in job.degraded_reasons)
    jm.shutdown()


def test_reduce_error_mid_tree_degrades_job(tmp_path):
    """One mid-tree reduce node erroring (its marker folded into a
    successful final summary) is a DEGRADED completion, not "done": the
    content is partially corrupted and the status must say so."""
    jm = JobManager(MockEngine(seed=0, fail_pattern="batch: 1/"), tmp_path,
                    config=jw.job_pipeline_config("mock"),
                    start_worker=False)
    job = jm.submit(jw.job_transcript())
    jm.run_job(job)
    assert job.status == "degraded"
    assert job.result["reduce_errors"] >= 1
    assert job.result["summary"]
    assert not job.result["summary"].startswith("[Error aggregating")
    jm.shutdown()


def test_graceful_shutdown_withholds_shutdown_induced_terminal(baseline,
                                                               tmp_path):
    """A GRACEFUL server restart mid-job must resume like a SIGKILL does:
    shutdown fast-fails the job's in-flight engine requests, and
    journaling that failure as terminal would leave the replacement
    server refusing to resume.  The terminal record is withheld when the
    manager is stopping; the replacement recovers, resumes the journaled
    chunks, and lands the baseline summary."""
    from lmrs_tpu.serving.server import EngineHTTPServer

    wal = tmp_path / f"{baseline['jid']}.wal"
    with faults.injected(FaultPlan(faults=[
            {"site": "journal.append", "every": 1, "action": "stall",
             "stall_s": 1.0}])):
        srv = EngineHTTPServer(jw.build_engine("mock"), port=0,
                               batch_window_s=0.01, jobs_dir=str(tmp_path),
                               pipeline_config=jw.job_pipeline_config("mock"))
        srv.start_background()
        _http("POST", f"http://{srv.host}:{srv.port}/v1/jobs",
              {"transcript": jw.job_transcript()})
        deadline = time.time() + 60
        while time.time() < deadline:
            if wal.exists() and sum(
                    1 for r in jl.replay(wal)[0]
                    if r["type"] == jl.REC_CHUNK) >= 2:
                break
            time.sleep(0.02)
        else:
            raise TimeoutError("never saw 2 journaled chunks")
        srv.shutdown()  # graceful: joins the worker 5s, then closes engine
        srv.jobs._worker.join(60)  # let the orphaned run wind down fully
        assert not srv.jobs._worker.is_alive()
    state = jl.rebuild_state(jl.replay(wal)[0])
    assert state["done"] is None, \
        "graceful shutdown journaled a terminal record — not resumable"
    assert len(state["chunks"]) >= 2
    srv2 = EngineHTTPServer(jw.build_engine("mock"), port=0,
                            batch_window_s=0.01, jobs_dir=str(tmp_path),
                            pipeline_config=jw.job_pipeline_config("mock"))
    srv2.start_background()
    try:
        doc = _poll_job(f"http://{srv2.host}:{srv2.port}",
                        baseline["jid"])
        assert doc["status"] == "done" and doc["recovered"]
        assert doc["progress"]["num_resumed_chunks"] >= 2
        assert doc["result"]["summary"] == baseline["summary"]
    finally:
        srv2.shutdown()


# ----------------------------------------------------------- HTTP surface


def _http(method: str, url: str, body: dict | None = None,
          timeout: float = 30.0):
    req = urllib.request.Request(
        url, data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method=method)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _poll_job(base: str, jid: str, deadline_s: float = 60.0) -> dict:
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        _, doc = _http("GET", f"{base}/v1/jobs/{jid}")
        if doc["status"] in ("done", "degraded", "failed", "cancelled"):
            return doc
        time.sleep(0.05)
    raise TimeoutError(f"job {jid} never terminal")


@pytest.fixture
def job_server(tmp_path):
    from lmrs_tpu.serving.server import EngineHTTPServer

    srv = EngineHTTPServer(jw.build_engine("mock"), port=0,
                           batch_window_s=0.01, jobs_dir=str(tmp_path),
                           pipeline_config=jw.job_pipeline_config("mock"))
    srv.start_background()
    yield srv, f"http://{srv.host}:{srv.port}", tmp_path
    srv.shutdown()


def test_job_api_lifecycle(job_server):
    srv, base, _d = job_server
    status, doc = _http("POST", f"{base}/v1/jobs",
                        {"transcript": jw.job_transcript()})
    assert status == 200 and doc["object"] == "job"
    jid = doc["id"]
    assert doc["status"] in ("queued", "running")
    final = _poll_job(base, jid)
    assert final["status"] == "done"
    assert final["result"]["summary"]
    assert final["progress"]["chunks_done"] == final["progress"]["num_chunks"]
    # duplicate POST converges on the same job (content-addressed)
    _, doc2 = _http("POST", f"{base}/v1/jobs",
                    {"transcript": jw.job_transcript()})
    assert doc2["id"] == jid and doc2["status"] == "done"
    # list + stats surfaces
    _, lst = _http("GET", f"{base}/v1/jobs")
    assert [d["id"] for d in lst["data"]] == [jid]
    _, metrics = _http("GET", f"{base}/metrics")
    assert metrics["jobs"]["by_status"].get("done") == 1
    # Prometheus exposition carries the lmrs_jobs_* family
    req = urllib.request.Request(f"{base}/metrics",
                                 headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=10) as r:
        text = r.read().decode()
    assert "lmrs_jobs_submitted_total 1" in text
    assert "lmrs_jobs_completed_total 1" in text
    assert "lmrs_jobs_journal_appends_total" in text
    # DELETE on a terminal job: terminal states stick
    status, doc3 = _http("DELETE", f"{base}/v1/jobs/{jid}")
    assert status == 200 and doc3["status"] == "done"


def test_job_api_validation(job_server):
    _srv, base, _d = job_server
    for bad in ({}, {"transcript": "not a dict"},
                {"transcript": {"segments": "nope"}}):
        with pytest.raises(urllib.error.HTTPError) as e:
            _http("POST", f"{base}/v1/jobs", bad)
        assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _http("POST", f"{base}/v1/jobs",
              {"transcript": jw.job_transcript(n=6),
               "params": {"no_such_knob": 1}})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _http("GET", f"{base}/v1/jobs/job-doesnotexist")
    assert e.value.code == 404


def test_job_api_disabled_is_501():
    from lmrs_tpu.serving.server import EngineHTTPServer

    srv = EngineHTTPServer(MockEngine(), port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _http("POST", f"http://{srv.host}:{srv.port}/v1/jobs",
                  {"transcript": jw.job_transcript(n=6)})
        assert e.value.code == 501
    finally:
        srv.shutdown()


def test_job_api_survives_server_sigkill(tmp_path):
    """The acceptance scenario at the HTTP tier: POST a job to a real
    lmrs-serve process, SIGKILL the process mid-map (journal paced by an
    append-stall plan), start a replacement server over the same jobs
    dir, and read back a token-identical summary with recovered=true and
    real resumed-chunk counts."""
    from lmrs_tpu.serving.server import EngineHTTPServer

    jobs_dir = tmp_path / "jobs"
    jobs_dir.mkdir()
    # uninterrupted reference, same config, separate dir
    ref_dir = tmp_path / "ref"
    jm = JobManager(jw.build_engine("mock"), ref_dir,
                    config=jw.job_pipeline_config("mock"),
                    start_worker=False)
    ref = jm.submit(jw.job_transcript())
    jm.run_job(ref)
    assert ref.status == "done"
    jm.shutdown()

    port = free_port()
    spec = tmp_path / "spec.json"
    spec.write_text(json.dumps({"mode": "serve", "port": port,
                                "jobs_dir": str(jobs_dir)}))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               LMRS_FAULT_PLAN=json.dumps({"faults": [
                   {"site": "journal.append", "every": 1,
                    "action": "stall", "stall_s": 0.15}]}))
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(__file__),
                                      "_job_worker.py"), str(spec)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    try:
        base = f"http://127.0.0.1:{port}"
        t0 = time.time()
        while time.time() - t0 < 60:
            if proc.poll() is not None:
                raise RuntimeError("server died: "
                                   + proc.stderr.read().decode()[-2000:])
            try:
                with urllib.request.urlopen(f"{base}/healthz", timeout=2):
                    break
            except OSError:
                time.sleep(0.1)
        status, doc = _http("POST", f"{base}/v1/jobs",
                            {"transcript": jw.job_transcript()})
        assert status == 200
        jid = doc["id"]
        assert jid == ref.job_id  # content-addressed across processes
        wal = jobs_dir / f"{jid}.wal"
        # kill mid-map: >=2 chunk records journaled, job not done
        chunks_seen = _wait_for_wal(wal, "chunk_done", 2)
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
        state = jl.rebuild_state(jl.replay(wal)[0])
        assert state["done"] is None, "kill landed after completion"
        assert len(state["chunks"]) >= 2
        del chunks_seen
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    # replacement server over the same jobs dir recovers + finishes
    srv = EngineHTTPServer(jw.build_engine("mock"), port=0,
                           batch_window_s=0.01, jobs_dir=str(jobs_dir),
                           pipeline_config=jw.job_pipeline_config("mock"))
    srv.start_background()
    try:
        base2 = f"http://{srv.host}:{srv.port}"
        final = _poll_job(base2, ref.job_id)
        assert final["status"] == "done"
        assert final["recovered"] is True
        assert final["progress"]["num_resumed_chunks"] >= 2
        assert final["result"]["summary"] == ref.result["summary"]
    finally:
        srv.shutdown()


def _wait_for_wal(wal: Path, rec_type: str, n: int,
                  deadline_s: float = 120.0) -> int:
    """Poll a journal until >= n records of rec_type are durably framed."""
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if wal.exists():
            recs, _ = jl.replay(wal)
            have = sum(1 for r in recs if r.get("type") == rec_type)
            if have >= n:
                return have
        time.sleep(0.02)
    raise TimeoutError(f"never saw {n} {rec_type} records in {wal}")


def test_router_forwards_job_api(tmp_path):
    """Fleet deployments: the front router-backed server has no local
    JobManager — /v1/jobs forwards to the backend that owns the journal,
    sticky by job id, and unknown ids scan the fleet."""
    from lmrs_tpu.serving.router import RouterEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    backend = EngineHTTPServer(jw.build_engine("mock"), port=0,
                               batch_window_s=0.01,
                               jobs_dir=str(tmp_path / "b1"),
                               pipeline_config=jw.job_pipeline_config("mock"))
    backend.start_background()
    router = RouterEngine([f"127.0.0.1:{backend.port}"])
    front = EngineHTTPServer(router, port=0, batch_window_s=0.01)
    front.start_background()
    try:
        base = f"http://{front.host}:{front.port}"
        status, doc = _http("POST", f"{base}/v1/jobs",
                            {"transcript": jw.job_transcript()})
        assert status == 200
        jid = doc["id"]
        final = _poll_job(base, jid)
        assert final["status"] == "done" and final["result"]["summary"]
        _, lst = _http("GET", f"{base}/v1/jobs")
        assert [d["id"] for d in lst["data"]] == [jid]
        assert lst["hosts_unreachable"] == 0
        # stickiness cache rebuilt after a router restart: a fresh router
        # resolves the id by scanning the fleet
        router2 = RouterEngine([f"127.0.0.1:{backend.port}"])
        front2 = EngineHTTPServer(router2, port=0, batch_window_s=0.01)
        front2.start_background()
        try:
            _, doc2 = _http("GET",
                            f"http://{front2.host}:{front2.port}/v1/jobs/{jid}")
            assert doc2["status"] == "done"
        finally:
            front2.shutdown()
            router2.shutdown()
        with pytest.raises(urllib.error.HTTPError) as e:
            _http("GET", f"{base}/v1/jobs/job-missing")
        assert e.value.code == 404
        # forwarding is counted on the router's exposition
        req = urllib.request.Request(f"{base}/metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=10) as r:
            text = r.read().decode()
        assert "lmrs_router_jobs_forwarded_total" in text
    finally:
        front.shutdown()
        router.shutdown()
        backend.shutdown()
