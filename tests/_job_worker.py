"""Subprocess entry point for the durable-job SIGKILL chaos scenarios.

Runs ONE durable job (JobManager over a mock or CPU-jax engine) inside
its own OS process so the parent test (tests/test_chaos.py,
tests/test_jobs.py) can SIGKILL it mid-map or mid-reduce by watching the
write-ahead journal grow, then resume the journal with its OWN engine
and assert the final greedy summary is token-identical to an
uninterrupted run.

The parent paces the child's journal appends with a ``journal.append``
stall fault plan (LMRS_FAULT_PLAN in the child env) so the kill window
between records is wide and machine-speed independent — stalls never
change WHAT is written, only when.

The config builders below are the single source of truth for both sides:
the parent resumes under the SAME PipelineConfig (and, for the jax arm,
the same engine/model geometry), so the job's config fingerprint matches
and the journal rehydrates instead of being set aside as stale.

Usage: ``python tests/_job_worker.py <spec.json>`` where the spec file
carries ``{"jobs_dir", "backend": "mock"|"jax", "transcript"}``.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def job_transcript(n: int = 30, seed: int = 1) -> dict:
    """Deterministic synthetic transcript (same schema as conftest's
    ``make_segments``; duplicated here so the child never imports the
    test-harness conftest)."""
    import random

    rng = random.Random(seed)
    words = ("the quarterly review covered the inference engine roadmap "
             "kernel design latency targets hiring plan and budget "
             "allocation for the serving tier").split()
    segs = []
    t = 0.0
    for i in range(n):
        dur = 2.0 + rng.random() * 6.0
        text = " ".join(rng.choice(words) for _ in range(8 + rng.randrange(14)))
        segs.append({"start": round(t, 2), "end": round(t + dur, 2),
                     "text": text.capitalize() + ".",
                     "speaker": f"SPEAKER_{i % 2:02d}"})
        t += dur + rng.random()
    return {"segments": segs}


def job_pipeline_config(backend: str = "mock"):
    """The (chunk, engine, reduce) surface both sides run under.  Small
    chunks force a multi-chunk map; a small reduce batch budget forces a
    hierarchical tree with several nodes, so "mid-reduce" is a real
    window.  temperature=0 end to end: the token-identity contract is
    greedy."""
    from lmrs_tpu.config import (ChunkConfig, EngineConfig, PipelineConfig,
                                 ReduceConfig)

    if backend == "jax":
        # the checkpointless tiny model generates near-empty text, so the
        # tree shape must hang on the deterministic [Time: ...] tags each
        # reduce input carries (~6 tokens/chunk): a budget well under the
        # total tag mass forces a multi-node hierarchical tree no matter
        # what the content-free weights emit
        reduce = ReduceConfig(max_tokens_per_batch=12, reserve_tokens=0,
                              max_summaries_per_batch=3)
    else:
        reduce = ReduceConfig(max_tokens_per_batch=300, reserve_tokens=50,
                              max_summaries_per_batch=3)
    return PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=150, overlap_tokens=0,
                          context_tokens=30, tokenizer="approx"),
        engine=EngineConfig(backend=backend, temperature=0.0, seed=0,
                            max_tokens=48, retry_delay=0.0),
        reduce=reduce,
    )


def build_engine(backend: str):
    """mock: instantaneous deterministic text.  jax: the chaos-soak
    geometry (tests/test_chaos.py ``chaos_model``) on a real continuous
    scheduler — tiny enough to compile in CI, real enough that the
    resume-side ``scheduler.audit()`` exercises page conservation."""
    if backend == "mock":
        from lmrs_tpu.engine.mock import MockEngine

        return MockEngine(seed=0)
    from lmrs_tpu.config import EngineConfig, ModelConfig
    from lmrs_tpu.engine.jax_engine import JaxEngine

    model = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                        dtype="float32")
    cfg = job_pipeline_config("jax").engine
    return JaxEngine(
        EngineConfig(backend="jax", scheduler="continuous",
                     max_tokens=cfg.max_tokens, temperature=0.0,
                     max_batch_slots=2, seed=0, decode_block=4,
                     page_size=16, num_pages=48),
        model)


def serve(spec: dict) -> int:
    """``mode: "serve"``: a real EngineHTTPServer OS process with the job
    API armed, under the SAME pipeline config the parent's replacement
    server will use — the restart-mid-job scenario needs fingerprint
    equality across the two server generations or the journal would be
    set aside as stale instead of resumed."""
    from lmrs_tpu.serving.server import EngineHTTPServer

    server = EngineHTTPServer(
        build_engine(spec.get("backend", "mock")),
        port=int(spec["port"]), batch_window_s=0.01,
        jobs_dir=spec["jobs_dir"],
        pipeline_config=job_pipeline_config(spec.get("backend", "mock")))
    server.serve_forever()
    return 0


def main(spec_path: str) -> int:
    spec = json.loads(Path(spec_path).read_text(encoding="utf-8"))
    # share the parent's persistent XLA compile cache (conftest.py): the
    # child's engine compiles the same tiny shapes the suite already built
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir", "/tmp/lmrs_jax_cache")
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 - mock arm / old jax: cache is optional
        pass
    if spec.get("mode") == "serve":
        return serve(spec)

    from lmrs_tpu.jobs.manager import JobManager

    backend = spec.get("backend", "mock")
    engine = build_engine(backend)
    manager = JobManager(engine, spec["jobs_dir"],
                         config=job_pipeline_config(backend),
                         start_worker=False)
    job = manager.submit(spec["transcript"])
    manager.run_job(job)
    print(json.dumps({"job_id": job.job_id, "status": job.status,
                      "summary": (job.result or {}).get("summary")}))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
