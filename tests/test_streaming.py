"""Streamed map→reduce (reduce/streaming.py + scheduler on_result hook)."""

from __future__ import annotations

import dataclasses

import pytest

from lmrs_tpu.config import (
    ChunkConfig, EngineConfig, ModelConfig, PipelineConfig, ReduceConfig,
)
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.pipeline import TranscriptSummarizer

from conftest import make_segments

TINY = ModelConfig(name="tiny-test", vocab_size=512, dim=64, n_layers=2,
                   n_heads=4, n_kv_heads=2, hidden_dim=128, max_seq_len=512)


# ------------------------------------------------------------ scheduler hook

def test_scheduler_streaming_submit_chain():
    """A result callback can submit new requests into the running stream."""
    from lmrs_tpu.engine.jax_engine import JaxEngine

    eng = JaxEngine(
        EngineConfig(backend="jax", max_tokens=8, max_batch_slots=2,
                     retry_delay=0.0, decode_block=4, num_pages=64,
                     page_size=16, temperature=0.0),
        TINY,
    )
    seen: list[int] = []

    def on_result(res, submit):
        seen.append(res.request_id)
        assert res.error is None
        if res.request_id == 0:
            submit([GenerationRequest(prompt="second wave", request_id=10,
                                      max_new_tokens=4)])
        elif res.request_id == 10:
            submit([GenerationRequest(prompt="third wave", request_id=20,
                                      max_new_tokens=4)])

    results = eng.generate_batch(
        [GenerationRequest(prompt="first", request_id=0, max_new_tokens=4)],
        on_result=on_result,
    )
    eng.shutdown()
    assert sorted(seen) == [0, 10, 20]
    assert [r.request_id for r in results] == [0, 10, 20]
    assert all(r.error is None for r in results)


def test_mock_drain_with_callback():
    eng = MockEngine()
    seen = []

    def on_result(res, submit):
        seen.append(res.request_id)
        if res.request_id == 0:
            submit([GenerationRequest(prompt="more", request_id=1)])

    out = eng.generate_batch([GenerationRequest(prompt="go", request_id=0)],
                             on_result=on_result)
    assert seen == [0, 1]
    assert len(out) == 2


# ------------------------------------------------------- executor streaming

class FlakyEngine:
    """Fails each distinct request id once, then succeeds."""

    schedules_internally = True

    def __init__(self):
        self.inner = MockEngine()
        self.failed_once: set[str] = set()

    def generate_batch(self, requests, on_result=None):
        from lmrs_tpu.engine.api import drain_with_callback

        def wave(reqs):
            out = []
            for r, res in zip(reqs, self.inner.generate_batch(reqs)):
                if r.prompt not in self.failed_once:
                    self.failed_once.add(r.prompt)
                    res = dataclasses.replace(
                        res, error="transient fault", finish_reason="error")
                out.append(res)
            return out

        if on_result is not None:
            return drain_with_callback(wave, requests, on_result)
        return wave(requests)

    def shutdown(self):
        pass

    def engine_metrics(self):
        return {}


def test_streaming_retry_resubmits_into_stream():
    ex = MapExecutor(FlakyEngine(), EngineConfig(retry_attempts=3,
                                                 retry_delay=0.0))
    finals = {}

    def on_final(res, submit):
        finals[res.request_id] = res

    ex.run_requests_streaming(
        [GenerationRequest(prompt=f"p{i}", request_id=i) for i in range(3)],
        on_final,
    )
    assert sorted(finals) == [0, 1, 2]
    assert all(r.error is None for r in finals.values())
    assert ex.failed_requests == 0
    assert ex.total_requests == 6  # 3 failures + 3 retried successes


def test_streaming_retry_exhaustion_degrades():
    ex = MapExecutor(MockEngine(fail_pattern="poison"),
                     EngineConfig(retry_attempts=2, retry_delay=0.0))
    finals = {}
    ex.run_requests_streaming(
        [GenerationRequest(prompt="fine", request_id=0),
         GenerationRequest(prompt="has poison inside", request_id=1)],
        lambda res, submit: finals.__setitem__(res.request_id, res),
    )
    assert finals[0].error is None
    assert finals[1].error is not None
    assert ex.failed_requests == 1


def test_streaming_rejects_negative_ids():
    ex = MapExecutor(MockEngine(), EngineConfig())
    with pytest.raises(ValueError):
        ex.run_requests_streaming(
            [GenerationRequest(prompt="x", request_id=-5)], lambda r, s: None)


# ------------------------------------------------------- pipeline end-to-end

def _cfg(streaming: bool, max_tokens_per_batch: int = 6000) -> PipelineConfig:
    return PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=400, context_tokens=100,
                          overlap_tokens=0),
        engine=EngineConfig(backend="mock", retry_delay=0.0, seed=0),
        reduce=ReduceConfig(max_tokens_per_batch=max_tokens_per_batch,
                            reserve_tokens=200, streaming=streaming),
    )


def test_pipeline_streaming_single_pass_matches_barrier():
    """Under-budget totals must produce the EXACT barrier-path result
    (single-pass decision + prompt are identical)."""
    data = {"segments": make_segments(40)}
    a = TranscriptSummarizer(_cfg(streaming=True)).summarize(data)
    b = TranscriptSummarizer(_cfg(streaming=False)).summarize(data)
    assert a["hierarchical"] is False and b["hierarchical"] is False
    assert a["summary"] == b["summary"]
    assert a["num_chunks"] == b["num_chunks"]


def test_pipeline_streaming_hierarchical():
    data = {"segments": make_segments(400)}
    cfg = _cfg(streaming=True, max_tokens_per_batch=700)
    stats = TranscriptSummarizer(cfg).summarize(data)
    assert stats["hierarchical"] is True
    assert stats["reduce_levels"] >= 2
    assert stats["summary"]
    assert stats["failed_requests"] == 0
    # stage timing keys still present (map + reduce tail)
    assert "map" in stats["stage_times"] and "reduce" in stats["stage_times"]

    barrier = TranscriptSummarizer(
        _cfg(streaming=False, max_tokens_per_batch=700)).summarize(data)
    assert barrier["hierarchical"] is True
    assert barrier["summary"]


def test_pipeline_streaming_with_resume(tmp_path):
    data = {"segments": make_segments(120)}
    dump = str(tmp_path / "chunks.json")
    s1 = TranscriptSummarizer(_cfg(streaming=True))
    first = s1.summarize(data, save_chunks=dump)
    s2 = TranscriptSummarizer(_cfg(streaming=True))
    second = s2.summarize(data, resume_from=dump)
    assert second["num_resumed_chunks"] == first["num_chunks"]
    assert second["summary"]


def test_pipeline_streaming_jax_engine():
    """Full pipeline over the continuous scheduler with live submission."""
    cfg = PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=300, context_tokens=100,
                          overlap_tokens=0, tokenizer="byte"),
        engine=EngineConfig(backend="jax", max_tokens=16, max_batch_slots=4,
                            retry_delay=0.0, decode_block=8, num_pages=128,
                            page_size=16, temperature=0.0),
        model=TINY,
        reduce=ReduceConfig(max_tokens_per_batch=300, reserve_tokens=100,
                            streaming=True),
    )
    s = TranscriptSummarizer(cfg)
    stats = s.summarize({"segments": make_segments(60)})
    s.shutdown()
    assert stats["summary"] is not None
    assert stats["failed_requests"] == 0
    assert stats["num_chunks"] > 1
