"""Disaggregated prefill/decode serving: KV page handoff tests.

Layers covered, bottom up:

* payload codec round trips (bf16 / int8 raw bytes, truncation rejected);
* ``PagedKVCache.export_sequence``/``import_sequence`` parity — bf16 and
  int8 pools, ragged lengths with a final partial page, import into a
  cache whose free-list state differs from the exporter's;
* ticket registry / import-log lifecycle (at-most-once, idempotent acks);
* scheduler-level token identity: greedy outputs byte-identical between
  colocated and disaggregated (prefill engine → wire payload → decode
  engine), prefix cache on and off, plus an int8-KV-pool arm — with the
  invariant auditor clean on BOTH engines, pinned-for-export pages
  accounted, and release/orphan-sweep restoring a fully free pool;
* the two-PROCESS mock topology the tier-1 disagg gate runs: prefill-role
  + decode-role ``lmrs-serve`` workers behind a pool-aware RouterEngine,
  greedy outputs token-identical to a colocated worker, a fault-armed
  variant (transfer fault → re-prefill fallback), and a decode-pod KILL
  mid-sequence completing via fallback.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest, preamble_key
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.engine.kv_cache import OutOfPages, PagedKVCache
from lmrs_tpu.serving.handoff import (ImportLog, TicketRegistry,
                                      decode_payload, encode_payload)
from lmrs_tpu.serving.router import RouterEngine

from tests.conftest import free_port


# ------------------------------------------------------------------ codec


def test_codec_round_trips_arrays_and_scalars():
    rng = np.random.default_rng(0)
    payload = {
        "kv_len": 19, "dtype": "float32", "tokens": [1, 2, 3],
        "nested_ok": {"a": 1},
        "k": rng.standard_normal((2, 3, 4)).astype(np.float32),
        "flags": rng.integers(-128, 127, (8,), dtype=np.int8),
    }
    out = decode_payload(encode_payload(payload))
    assert out["kv_len"] == 19 and out["tokens"] == [1, 2, 3]
    assert out["nested_ok"] == {"a": 1}
    np.testing.assert_array_equal(out["k"], payload["k"])
    np.testing.assert_array_equal(out["flags"], payload["flags"])
    assert out["flags"].dtype == np.int8


def test_codec_round_trips_bfloat16():
    import ml_dtypes

    arr = np.arange(12, dtype=np.float32).reshape(3, 4).astype(
        ml_dtypes.bfloat16)
    out = decode_payload(encode_payload({"k": arr}))
    assert out["k"].dtype == arr.dtype
    np.testing.assert_array_equal(out["k"].astype(np.float32),
                                  arr.astype(np.float32))


def test_codec_rejects_truncation():
    """A transfer fault mid-payload leaves a short blob; every truncation
    point must raise, never yield silently-short page data."""
    blob = encode_payload({"kv_len": 5,
                           "k": np.ones((4, 4), np.float32)})
    for cut in (0, 4, len(blob) // 2, len(blob) - 1):
        with pytest.raises(ValueError):
            decode_payload(blob[:cut])


# ------------------------------------------------- cache export / import


def _cache_model() -> ModelConfig:
    return ModelConfig(vocab_size=64, dim=32, n_layers=3, n_heads=4,
                       n_kv_heads=2, hidden_dim=64, max_seq_len=256,
                       dtype="float32")


def _fill_sequence(cache: PagedKVCache, seq, rng) -> None:
    """Write a distinct random pattern into every exported page (all
    layers), straight into the pools."""
    import jax.numpy as jnp

    phys = cache._phys_ids(seq.pages)
    shape = (len(phys),) + cache.k.shape[1:]
    if str(cache.k.dtype) == "int8":
        k = rng.integers(-127, 127, shape).astype(np.int8)
        v = rng.integers(-127, 127, shape).astype(np.int8)
    else:
        k = rng.standard_normal(shape).astype(np.float32)
        v = rng.standard_normal(shape).astype(np.float32)
    cache.k = cache.k.at[jnp.asarray(phys)].set(jnp.asarray(k, cache.k.dtype))
    cache.v = cache.v.at[jnp.asarray(phys)].set(jnp.asarray(v, cache.v.dtype))


@pytest.mark.parametrize("kv_dtype", [None, "bfloat16", "int8"])
def test_cache_export_import_round_trip(kv_dtype):
    """Page-set gather → wire → scatter parity: ragged length with a final
    partial page, destination free-list state deliberately different from
    the source's."""
    import jax

    mc = _cache_model()
    src = PagedKVCache(mc, num_pages=16, page_size=8, max_pages_per_slot=8,
                       kv_dtype=kv_dtype)
    rng = np.random.default_rng(7)
    length = 19  # 3 pages, final page holds 3 of 8 tokens
    seq = src.open_sequence(length)
    assert len(seq.pages) == 3
    _fill_sequence(src, seq, rng)
    payload = decode_payload(encode_payload(
        src.export_sequence(seq, length)))
    assert payload["kv_len"] == length and payload["n_pages"] == 3

    # destination with different geometry headroom and a perturbed free
    # list: pages already handed out, so imported phys ids differ
    dst = PagedKVCache(mc, num_pages=24, page_size=8, max_pages_per_slot=8,
                       kv_dtype=kv_dtype)
    held = dst.alloc_pages(5)
    seq2 = dst.import_sequence(payload)
    assert seq2.length == length
    assert set(seq2.pages).isdisjoint(held)

    got_k = np.asarray(jax.device_get(
        dst.k[np.asarray(dst._phys_ids(seq2.pages))]))
    want_k = np.asarray(payload["k"]).reshape(got_k.shape)
    got_v = np.asarray(jax.device_get(
        dst.v[np.asarray(dst._phys_ids(seq2.pages))]))
    want_v = np.asarray(payload["v"]).reshape(got_v.shape)
    np.testing.assert_array_equal(
        got_k.astype(np.float32), want_k.astype(np.float32))
    np.testing.assert_array_equal(
        got_v.astype(np.float32), want_v.astype(np.float32))

    dst.close_sequence(seq2)
    dst.allocator.free(held)
    src.close_sequence(seq)
    assert src.allocator.free_count == 15
    assert dst.allocator.free_count == 23


def test_cache_import_rejects_incompatible_payload():
    mc = _cache_model()
    src = PagedKVCache(mc, num_pages=16, page_size=8, max_pages_per_slot=8)
    seq = src.open_sequence(10)
    payload = src.export_sequence(seq, 10)

    other = PagedKVCache(mc, num_pages=16, page_size=16,
                         max_pages_per_slot=8)
    with pytest.raises(ValueError, match="page_size"):
        other.import_sequence(payload)
    quant = PagedKVCache(mc, num_pages=16, page_size=8,
                         max_pages_per_slot=8, kv_dtype="int8")
    with pytest.raises(ValueError, match="dtype"):
        quant.import_sequence(payload)
    # a rejected import allocates nothing
    assert other.allocator.free_count == 15
    assert quant.allocator.free_count == 15


def test_cache_import_backpressures_on_full_pool():
    mc = _cache_model()
    src = PagedKVCache(mc, num_pages=8, page_size=8, max_pages_per_slot=6)
    seq = src.open_sequence(30)  # 4 pages
    payload = src.export_sequence(seq, 30)
    dst = PagedKVCache(mc, num_pages=8, page_size=8, max_pages_per_slot=6)
    held = dst.alloc_pages(5)  # 2 free < 4 needed
    with pytest.raises(OutOfPages):
        dst.import_sequence(payload)
    dst.allocator.free(held)
    s2 = dst.import_sequence(payload)  # now fits
    assert len(s2.pages) == 4


# --------------------------------------------------- registry / dedup


def test_ticket_registry_at_most_once():
    t = [100.0]
    reg = TicketRegistry(clock=lambda: t[0])
    tid = reg.create(7, deadline_t=110.0)
    assert reg.lookup(tid)["rid"] == 7
    assert reg.consume(tid) == 7
    assert reg.consume(tid) is None  # duplicate ack: idempotent reject
    assert reg.lookup(tid) is None   # consumed: no more fetches
    # expiry: un-acked ticket surfaces as an orphan exactly once
    tid2 = reg.create(8, deadline_t=105.0)
    t[0] = 106.0
    assert reg.lookup(tid2) is None
    assert reg.consume(tid2) is None  # late ack after expiry: rejected
    swept = reg.sweep()
    assert swept == [(tid2, 8, False)]  # tid (deadline 110) still tabled
    t[0] = 111.0
    assert reg.sweep() == [(tid, 7, True)]  # consumed: NOT an orphan
    assert reg.sweep() == []


def test_import_log_dedups_and_bounds():
    log = ImportLog(cap=3)
    assert log.add("a") and not log.add("a")
    for x in "bcd":
        assert log.add(x)
    assert not log.seen("a")  # evicted by the cap
    assert log.seen("d")


# ------------------------------------- scheduler-level token identity


def _engine_cfg(**kw) -> EngineConfig:
    base = dict(backend="jax", scheduler="continuous", max_tokens=64,
                max_batch_slots=2, seed=0, decode_block=4, page_size=16,
                num_pages=48, handoff_ttl_s=30.0)
    base.update(kw)
    return EngineConfig(**base)


def _model() -> ModelConfig:
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


@pytest.fixture(scope="module", params=["cache_on", "cache_off", "int8"])
def trio(request):
    """(colocated, prefill, decode) engines sharing weights/config — the
    three pods of the disaggregation parity matrix."""
    kw = {}
    if request.param == "cache_off":
        kw["prefix_cache"] = False
    elif request.param == "int8":
        kw["kv_quantize"] = "int8"
        kw["page_size"] = 32  # int8 VMEM tiling wants page_size % 32 == 0
    engines = [JaxEngine(_engine_cfg(**kw), _model()) for _ in range(3)]
    yield request.param, engines
    for e in engines:
        e.shutdown()


def _greedy(prompt: str, rid: int, **kw) -> GenerationRequest:
    return GenerationRequest(prompt=prompt, request_id=rid,
                             temperature=0.0, max_new_tokens=10, **kw)


def test_disagg_matches_colocated_greedy(trio):
    """The acceptance A/B: token-identical colocated vs prefill→decode,
    both engine auditors clean across the whole transaction (pinned
    pages accounted while live, zero leaks after release)."""
    mode, (colo, pre, dec) = trio
    prompts = ["the quick brown fox jumps over the lazy dog",
               "the quick brown fox jumps over the fence again"]
    if mode == "cache_on":
        prompts.append(prompts[0])  # warm prefix-cache hit on a repeat
    for i, prompt in enumerate(prompts):
        base = colo.generate_batch([_greedy(prompt, i)])[0]
        assert base.completion_tokens > 1, "workload must outlive token 1"

        res_p = pre.generate_batch(
            [_greedy(prompt, i, handoff_export=True)])[0]
        assert res_p.finish_reason == "handoff"
        assert res_p.completion_tokens == 1
        assert base.text.startswith(res_p.text)
        assert pre._scheduler.pinned_handoffs()[i] >= 1
        assert pre._scheduler.audit() == []  # pinned class accounted

        payload = decode_payload(encode_payload(pre.export_handoff(i)))
        res_d = dec.generate_batch(
            [_greedy(prompt, i, handoff_state=payload)])[0]
        assert res_d.text == base.text
        assert res_d.finish_reason == base.finish_reason
        assert res_d.completion_tokens == base.completion_tokens

        assert pre.release_handoff(i) >= 1
        assert pre.release_handoff(i) == 0  # idempotent (duplicate ack)
        assert pre._scheduler.audit() == []
        assert dec._scheduler.audit() == []
    assert pre._scheduler.pinned_handoffs() == {}


def test_terminal_first_token_never_pins(trio):
    """A 1-token budget completes on the prefill engine (nothing left to
    hand off): normal finish, nothing pinned."""
    _, (_, pre, _) = trio
    res = pre.generate_batch([GenerationRequest(
        prompt="short", request_id=90, temperature=0.0,
        max_new_tokens=1, handoff_export=True)])[0]
    assert res.finish_reason == "length"
    assert res.completion_tokens == 1
    assert pre._scheduler.pinned_handoffs() == {}
    assert pre._scheduler.audit() == []


def test_import_rejects_token_mismatch(trio):
    """Payload kv_len disagreeing with the local prompt encoding is a
    MARKED error (tokenizer/config drift between pods must never resume
    silently corrupt), and the pool stays clean."""
    _, (_, pre, dec) = trio
    pre.generate_batch([_greedy("mismatch probe prompt", 91,
                                handoff_export=True)])
    payload = dict(pre.export_handoff(91))
    res = dec.generate_batch(
        [_greedy("a different prompt entirely, much longer than before",
                 91, handoff_state=payload)])[0]
    assert res.finish_reason == "error"
    assert "handoff import failed" in res.error
    assert dec._scheduler.audit() == []
    pre.release_handoff(91)
    assert pre._scheduler.audit() == []


def test_engine_orphan_sweep_reclaims_pins(trio):
    """A pin whose ticket deadline passes is reclaimed by the engine-side
    sweep, counted as orphaned pages, leaving a clean pool."""
    _, (_, pre, _) = trio
    pre.generate_batch([_greedy("orphan sweep probe", 92,
                                handoff_export=True)])
    sched = pre._scheduler
    assert sched.pinned_handoffs()
    before = sched.metrics["handoff_orphaned_pages"]
    released = pre.sweep_handoffs(now=time.time() + 3600.0)
    assert released >= 1
    assert sched.pinned_handoffs() == {}
    assert sched.metrics["handoff_orphaned_pages"] == before + released
    assert sched.audit() == []


def test_export_fault_degrades_to_marked_error():
    """An injected ``handoff.export`` fault at pin time costs THAT request
    (marked error the router can act on), never the pool."""
    from lmrs_tpu.testing import faults
    from lmrs_tpu.testing.faults import FaultPlan

    eng = JaxEngine(_engine_cfg(), _model())
    try:
        plan = FaultPlan(seed=3, faults=[{"site": "handoff.export",
                                          "at": [1]}])
        with faults.injected(plan):
            res = eng.generate_batch(
                [_greedy("export fault probe", 0, handoff_export=True)])[0]
        assert res.finish_reason == "error"
        assert "handoff export failed" in res.error
        assert eng._scheduler.pinned_handoffs() == {}
        assert eng._scheduler.audit() == []
        # engine still healthy for the next request
        ok = eng.generate_batch([_greedy("export fault probe", 1)])[0]
        assert ok.error is None
    finally:
        eng.shutdown()


def test_recovery_frees_pinned_pages():
    """A dispatch fault while exports are pinned: recovery must free the
    pinned pages through the allocator (which SURVIVES pool reallocation)
    — dropping the records without close_sequence would shrink the free
    pool forever — and later ticket fetches must 410, routing the request
    to the re-prefill fallback."""
    from lmrs_tpu.testing import faults
    from lmrs_tpu.testing.faults import FaultPlan

    eng = JaxEngine(_engine_cfg(), _model())
    try:
        sched = eng._scheduler
        free0 = sched.cache.allocator.free_count
        res = eng.generate_batch(
            [_greedy("recovery pin probe", 0, handoff_export=True)])[0]
        assert res.finish_reason == "handoff"
        assert sched.pinned_handoffs()
        plan = FaultPlan(seed=5, faults=[{"site": "scheduler.step",
                                          "at": [1], "max_fires": 1}])
        with faults.injected(plan):
            try:
                eng.generate_batch([_greedy("crash run", 1)])
            except Exception:  # noqa: BLE001 - the injected crash
                pass
        assert sched.pinned_handoffs() == {}
        assert sched.cache.allocator.free_count == free0
        assert sched.audit() == []
        with pytest.raises(KeyError):
            eng.export_handoff(0)  # ticket gone -> serving layer 410s
        ok = eng.generate_batch([_greedy("post recovery", 2)])[0]
        assert ok.error is None
        assert sched.audit() == []
    finally:
        eng.shutdown()


# ------------------------------------ two-process mock topology (gate)


_PROMPT = ("Transcript section: The committee reviewed the budget at "
           "length. Afterwards the chair summarized the next steps for "
           "the quarter in detail. Finally the group agreed to reconvene "
           "on Tuesday to close the remaining items.")


def _spawn_worker(port: int, role: str, extra_env: dict | None = None,
                  ttl: float = 30.0) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    return subprocess.Popen(
        [sys.executable, "-m", "lmrs_tpu.serving.cli",
         "--backend", "mock", "--port", str(port), "--role", role,
         "--handoff-ttl", str(ttl), "-q"],
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _wait_healthy(url: str, proc, deadline_s: float = 60.0) -> dict:
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if proc.poll() is not None:
            raise RuntimeError(
                f"worker died rc={proc.returncode}: "
                f"{proc.stderr.read().decode()[-2000:]}")
        try:
            with urllib.request.urlopen(f"{url}/healthz", timeout=2) as r:
                if r.status == 200:
                    return json.loads(r.read())
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"{url} never became healthy")


def _teardown(procs) -> None:
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


@pytest.fixture(scope="module")
def mock_topology():
    """colocated worker + prefill-role worker + decode-role worker, all
    REAL lmrs-serve OS processes (mock backend, identical seed)."""
    ports = [free_port() for _ in range(3)]
    procs = [_spawn_worker(ports[0], "both"),
             _spawn_worker(ports[1], "prefill"),
             # the decode worker carries a fault plan wired to fire a
             # transfer fault at its SECOND import (the fault-armed gate
             # variant runs against the same topology)
             _spawn_worker(ports[2], "decode", extra_env={
                 "LMRS_FAULT_PLAN": json.dumps({"seed": 5, "faults": [
                     {"site": "handoff.transfer", "at": [2],
                      "max_fires": 1}]})})]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    try:
        roles = [_wait_healthy(u, p)["role"]
                 for u, p in zip(urls, procs)]
        assert roles == ["both", "prefill", "decode"]
        yield ports, procs
    finally:
        _teardown(procs)


def test_two_process_disagg_token_identical(mock_topology):
    """The tier-1 disagg gate: greedy output through the prefill→decode
    topology is byte-identical to the colocated worker's."""
    ports, _ = mock_topology
    colo = RouterEngine([f"127.0.0.1:{ports[0]}"])
    disagg = RouterEngine([], prefill_hosts=[f"127.0.0.1:{ports[1]}"],
                          decode_hosts=[f"127.0.0.1:{ports[2]}"])
    try:
        req = GenerationRequest(prompt=_PROMPT, request_id=0,
                                temperature=0.0)
        base = colo.generate_batch([req])[0]
        assert base.error is None and base.text
        res = disagg.generate_batch([GenerationRequest(
            prompt=_PROMPT, request_id=0, temperature=0.0)])[0]
        assert res.error is None
        assert res.text == base.text
        assert disagg._handoffs == 1 and disagg._handoff_fallbacks == 0
        # pool-aware health surfaces per role
        m = disagg.engine_metrics()
        assert m["pools"]["prefill"]["size"] == 1
        assert m["pools"]["decode"]["healthy"] == 1
        prom = disagg.prometheus_metrics()
        assert 'lmrs_router_pool_size{pool="decode"}' in prom
        assert "lmrs_handoff_total" in prom
    finally:
        colo.shutdown()
        disagg.shutdown()


def test_two_process_fault_armed_transfer_falls_back(mock_topology):
    """Fault-armed variant: the decode worker's plan kills its second
    payload transfer mid-read; the router degrades to colocated
    re-prefill and the request still completes with the right text."""
    ports, _ = mock_topology
    colo = RouterEngine([f"127.0.0.1:{ports[0]}"])
    disagg = RouterEngine([], prefill_hosts=[f"127.0.0.1:{ports[1]}"],
                          decode_hosts=[f"127.0.0.1:{ports[2]}"])
    try:
        want = colo.generate_batch([GenerationRequest(
            prompt=_PROMPT, request_id=0, temperature=0.0)])[0].text
        # two requests so the at=[2] trigger is reached whether or not the
        # token-identical test already consumed transfer occurrence 1
        # (pinned-scenario robustness under -k selections)
        for rid in (1, 2):
            res = disagg.generate_batch([GenerationRequest(
                prompt=_PROMPT, request_id=rid, temperature=0.0)])[0]
            assert res.error is None
            assert res.text == want
        assert disagg._handoff_fallbacks >= 1
        assert disagg._handoff_retries >= 1
    finally:
        colo.shutdown()
        disagg.shutdown()


# ------------------------------------- cross-host KV migration (fabric)


_MIG_SYS = "Respond with the summary content only."
_MIG_PRE = ("You are summarizing one section of a much longer transcript. "
            "Keep every fact, decision, name, and number. ")


def _mig_request(rid: int, chunk: str = "Chunk A: milestone nine shipped."
                 ) -> GenerationRequest:
    return GenerationRequest(prompt=_MIG_PRE + chunk, request_id=rid,
                             temperature=0.0, system_prompt=_MIG_SYS,
                             cache_prefix=len(_MIG_PRE))


def _http_json(method: str, url: str, body: dict | None = None,
               timeout: float = 10.0) -> tuple[int, dict]:
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            raw = r.read()
            return r.status, (json.loads(raw) if raw else {})
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, (json.loads(raw) if raw else {})


@pytest.fixture(scope="module")
def kv_pair():
    """Two colocated-role mock workers, identical seed — the minimal
    fabric for cross-host page-set migration."""
    ports = [free_port() for _ in range(2)]
    procs = [_spawn_worker(p, "both") for p in ports]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    try:
        for u, p in zip(urls, procs):
            _wait_healthy(u, p)
        yield ports, urls
    finally:
        _teardown(procs)


def test_two_process_kv_migration_token_identity(kv_pair):
    """The migration wire end to end across two OS processes: warm a
    preamble on A, export→pull-import→ack to B, duplicate import 409s,
    the consumed ticket 410s, and B then serves the SAME greedy text
    with the migrated entry counting as a warm prefix hit."""
    ports, urls = kv_pair
    a = RouterEngine([f"127.0.0.1:{ports[0]}"])
    b = RouterEngine([f"127.0.0.1:{ports[1]}"])
    try:
        want = a.generate_batch([_mig_request(0)])[0]
        assert want.error is None and want.text
        key = preamble_key(_MIG_SYS, _mig_request(0).prompt,
                           len(_MIG_PRE))
        st, tdoc = _http_json("POST", urls[0] + "/v1/kv/export",
                              {"preamble": key})
        assert st == 200 and tdoc["object"] == "kv.ticket"
        assert tdoc["tokens"] > 0 and tdoc["bytes"] > 0
        src = f"127.0.0.1:{ports[0]}"
        st, idoc = _http_json("POST", urls[1] + "/v1/kv/import",
                              {"ticket": tdoc["ticket"], "source": src})
        assert st == 200 and idoc["status"] == "imported"
        assert idoc["imported_tokens"] == tdoc["tokens"]
        # lost-ack replay: the duplicate import is rejected idempotently
        st, _ = _http_json("POST", urls[1] + "/v1/kv/import",
                           {"ticket": tdoc["ticket"], "source": src})
        assert st == 409
        # the acked ticket's blob is gone from the source
        st, _ = _http_json("GET", urls[0] + f"/v1/kv/{tdoc['ticket']}")
        assert st == 410
        # B serves the preamble warm: identical text, fabric tokens up
        got = b.generate_batch([_mig_request(1)])[0]
        assert got.error is None and got.text == want.text
        st, m = _http_json("GET", urls[1] + "/metrics")
        assert st == 200
        assert m["engine"]["kv_migrate"]["imports"] >= 1
        assert m["engine"]["kv_migrate"]["tokens_imported"] >= tdoc["tokens"]
        assert m["engine"]["prefix_cache"]["hits"] >= 1
        assert "pinned_bytes" in m["kv_migrate"]  # ticket stats ride along
    finally:
        a.shutdown()
        b.shutdown()


def test_export_unknown_preamble_404s(kv_pair):
    _ports, urls = kv_pair
    st, doc = _http_json("POST", urls[0] + "/v1/kv/export",
                         {"preamble": "never-seen-hash"})
    assert st == 404
    assert "not warm" in doc["error"]["message"]
    st, _ = _http_json("POST", urls[0] + "/v1/kv/export", {})
    assert st == 400


def test_import_bad_ticket_and_unreachable_source(kv_pair):
    """An import whose pull fails (dead source / unknown ticket) answers
    an error and installs nothing — the importer must stay clean for the
    cold-resume fallback."""
    ports, urls = kv_pair
    st, _ = _http_json("POST", urls[1] + "/v1/kv/import",
                       {"ticket": "bogus-ticket",
                        "source": f"127.0.0.1:{ports[0]}"})
    assert st >= 400
    st, _ = _http_json("POST", urls[1] + "/v1/kv/import",
                       {"ticket": "t", "source": "127.0.0.1:1"})
    assert st >= 400
    st, _ = _http_json("POST", urls[1] + "/v1/kv/import", {})
    assert st == 400


def test_kv_ticket_expiry_orphan_sweeps_pinned_blob():
    """A kv page-set ticket whose ack is LOST must not pin its blob
    forever: the orphan sweep drops it at the ticket deadline (injected
    clock), after which the fetch answers 410; an ACKED ticket frees its
    blob immediately and sweeps silently."""
    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    srv = EngineHTTPServer(MockEngine(seed=0), port=0,
                           batch_window_s=0.01, handoff_ttl_s=30.0)
    srv.start_background()
    url = f"http://127.0.0.1:{srv.port}"
    try:
        assert srv.engine.generate_batch([_mig_request(0)])[0].error is None
        key = preamble_key(_MIG_SYS, _mig_request(0).prompt, len(_MIG_PRE))
        st, tdoc = _http_json("POST", url + "/v1/kv/export",
                              {"preamble": key})
        assert st == 200
        assert srv.kv_stats()["pinned_bytes"] > 0
        # inside the TTL window nothing is reclaimed
        assert srv.sweep_handoffs(time.time()) == 0
        assert srv.kv_stats()["pinned_bytes"] > 0
        # past the deadline the un-acked blob is orphan-swept
        assert srv.sweep_handoffs(time.time() + 60.0) >= 1
        assert srv.kv_stats()["pinned_bytes"] == 0
        st, _ = _http_json("GET", url + f"/v1/kv/{tdoc['ticket']}")
        assert st == 410
        # acked ticket: blob freed at ack, the sweep finds no orphan
        st, t2 = _http_json("POST", url + "/v1/kv/export",
                            {"preamble": key})
        assert st == 200
        st, _ = _http_json("POST", url + f"/v1/kv/{t2['ticket']}/ack")
        assert st == 200
        assert srv.kv_stats()["pinned_bytes"] == 0
        assert srv.sweep_handoffs(time.time() + 120.0) == 0
        st, _ = _http_json("POST", url + f"/v1/kv/{t2['ticket']}/ack")
        assert st == 410  # duplicate ack: idempotent refusal
    finally:
        srv.shutdown()


def test_router_drain_migrates_warm_kv_and_repins(kv_pair):
    """Fleet-level drain: the router moves the draining host's warm
    preambles to the sibling over the /v1/kv wire, purges its sticky
    caches, re-pins, and follow-up traffic hits warm on the sibling."""
    ports, urls = kv_pair
    router = RouterEngine([f"127.0.0.1:{ports[0]}",
                           f"127.0.0.1:{ports[1]}"])
    try:
        chunk = "Chunk D: the drain rehearsal minutes."
        want = router.generate_batch([_mig_request(10, chunk)])[0]
        assert want.error is None
        # find which host the prefix landed on; drain exactly that one
        key = preamble_key(_MIG_SYS, _mig_request(10, chunk).prompt,
                           len(_MIG_PRE))
        warm_idx = None
        for i, u in enumerate(urls):
            _st, m = _http_json("GET", u + "/metrics")
            rows = {r["hash"] for r in m.get("prefix_summary") or ()}
            if key in rows:
                warm_idx = i
                break
        assert warm_idx is not None
        warm, sib = ports[warm_idx], ports[1 - warm_idx]
        assert router.drain_host(f"127.0.0.1:{warm}")
        deadline = time.time() + 20.0
        while (router.migrations_pending(f"127.0.0.1:{warm}")
               and time.time() < deadline):
            time.sleep(0.1)
        assert not router.migrations_pending(f"127.0.0.1:{warm}")
        assert router._kv_moves >= 1
        _st, m = _http_json("GET", f"http://127.0.0.1:{sib}/metrics")
        assert m["engine"]["kv_migrate"]["imports"] >= 1
        # the drained host left every sticky structure
        with router._job_lock:
            assert f"127.0.0.1:{warm}" not in router._job_hosts.values()
        # the same preamble now serves warm from the sibling (the
        # drained host is out of the dispatch order), identical text
        got = router.generate_batch([_mig_request(11, chunk)])[0]
        assert got.error is None and got.text == want.text
        em = router.engine_metrics()
        assert em["kv_migrate"]["moves"] >= 1
        prom = router.prometheus_metrics()
        assert "lmrs_kv_migrate_moves_total" in prom
    finally:
        router.shutdown()


def test_kv_migrate_kill_switch_parity(monkeypatch):
    """LMRS_KV_MIGRATE=0 end to end: every /v1/kv route 501s, the
    /metrics documents carry no kv_migrate key anywhere, and a drain
    still purges sticky state without attempting a single move."""
    port = free_port()
    proc = _spawn_worker(port, "both",
                         extra_env={"LMRS_KV_MIGRATE": "0"})
    url = f"http://127.0.0.1:{port}"
    monkeypatch.setenv("LMRS_KV_MIGRATE", "0")
    router = RouterEngine([f"127.0.0.1:{port}"])
    try:
        _wait_healthy(url, proc)
        assert not router.kv_migrate
        res = router.generate_batch([_mig_request(0)])[0]
        assert res.error is None
        for call in (("POST", "/v1/kv/export", {"preamble": "x"}),
                     ("POST", "/v1/kv/import", {"ticket": "t",
                                                "source": "s"}),
                     ("GET", "/v1/kv/t", None),
                     ("POST", "/v1/kv/t/ack", None)):
            st, doc = _http_json(call[0], url + call[1], call[2])
            assert st == 501, call
            assert "LMRS_KV_MIGRATE=0" in doc["error"]["message"]
        _st, m = _http_json("GET", url + "/metrics")
        assert "kv_migrate" not in m
        assert "kv_migrate" not in m["engine"]
        assert router.drain_host(f"127.0.0.1:{port}")
        assert not router.migrations_pending(f"127.0.0.1:{port}")
        assert router._kv_moves == 0 and router._kv_failures == 0
        assert "kv_migrate" not in router.engine_metrics()
        assert "lmrs_kv_migrate" not in router.prometheus_metrics()
    finally:
        router.shutdown()
        _teardown([proc])


def test_two_process_decode_pod_killed_mid_sequence(mock_topology):
    """Killing the decode pod outright: the first request after the kill
    completes via re-prefill fallback (the acceptance chaos criterion's
    cross-process arm; the audited jax arm lives in test_chaos.py)."""
    ports, procs = mock_topology
    colo = RouterEngine([f"127.0.0.1:{ports[0]}"])
    disagg = RouterEngine([], prefill_hosts=[f"127.0.0.1:{ports[1]}"],
                          decode_hosts=[f"127.0.0.1:{ports[2]}"])
    try:
        want = colo.generate_batch([GenerationRequest(
            prompt=_PROMPT, request_id=0, temperature=0.0)])[0].text
        procs[2].kill()
        procs[2].wait(timeout=10)
        res = disagg.generate_batch([GenerationRequest(
            prompt=_PROMPT, request_id=2, temperature=0.0)])[0]
        assert res.error is None
        assert res.text == want
        assert disagg._handoff_fallbacks >= 1
    finally:
        colo.shutdown()
        disagg.shutdown()
