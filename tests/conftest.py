"""Test harness config.

Sharding tests run on a virtual 8-device CPU mesh — set platform flags BEFORE
jax is imported anywhere (SURVEY.md §4: emulate TP/DP without TPUs via
``xla_force_host_platform_device_count``).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sitecustomize calls jax.config.update("jax_platforms", "axon,cpu")
# in EVERY interpreter, overriding the env var — force it back to cpu before
# any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite's wall-clock is dominated by
# CPU XLA compiles of the engine programs (the quality-gate file alone
# compiles ~40 min cold); cached, repeat runs skip every previously-seen
# shape.  Harmless if unsupported — correctness never depends on it.
try:
    jax.config.update("jax_compilation_cache_dir", "/tmp/lmrs_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception:  # older jax: no persistent cache knobs
    pass

import json
import math
import random
from pathlib import Path

import pytest

REFERENCE_EXAMPLE = Path("/root/reference/transcript-example.json")


def free_port() -> int:
    """OS-assigned local port (shared by the multi-process tests).  The
    probe socket closes before the caller binds, so a collision is
    possible (TOCTOU) — callers that can retry should (test_distributed's
    pair fixture does)."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def make_segments(n: int = 200, n_speakers: int = 2, seed: int = 0) -> list[dict]:
    """Deterministic synthetic diarized transcript (schema: README.md:162-175)."""
    rng = random.Random(seed)
    words = (
        "the project timeline depends on shipping the new inference engine "
        "before the quarterly review so we must finalize the kernel design "
        "budget allocation and hiring plan while keeping latency targets"
    ).split()
    segs = []
    t = 0.0
    for i in range(n):
        dur = 2.0 + rng.random() * 6.0
        text = " ".join(rng.choice(words) for _ in range(8 + rng.randrange(18)))
        segs.append(
            {
                "start": round(t, 2),
                "end": round(t + dur, 2),
                "text": text.capitalize() + ".",
                "speaker": f"SPEAKER_{(i // 5) % n_speakers:02d}",
            }
        )
        t += dur + rng.random()
    return segs


@pytest.fixture
def segments() -> list[dict]:
    return make_segments()


@pytest.fixture
def transcript(segments) -> dict:
    return {"segments": segments}


@pytest.fixture
def example_transcript() -> dict:
    if not REFERENCE_EXAMPLE.exists():
        pytest.skip("reference example transcript not available")
    return json.loads(REFERENCE_EXAMPLE.read_text())
