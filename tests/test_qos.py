"""Multi-tenant QoS enforcement + fleet elasticity (ISSUE 17).

The tier-1 ``qos`` gate: the fair-share policy rules must be
deterministic given the same usage window, greedy outputs must be
byte-identical with ``LMRS_QOS`` on vs off (QoS reorders admission,
never generation), the mock admission gate must order waiters by class
then deficit when armed and strictly FIFO when disarmed, ledger
conservation must survive concurrent TenantStampEngine traffic through
a slot-limited gate, anonymous ingress must bill to the minted
``default`` tenant, the overflow counter must fire past the tenant
cardinality cap, the router's elasticity surface (add/drain/idle/
remove) must hold its invariants, and the autoscaler control loop must
scale up on burn, drain before removal, and never touch
operator-configured capacity.
"""

from __future__ import annotations

import http.client
import itertools
import json
import logging
import threading
import time

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import (GenerationRequest, GenerationResult,
                                 TenantStampEngine)
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.fleet.autoscale import Autoscaler, maybe_autoscaler
from lmrs_tpu.fleet.qos import (QoSPolicy, class_rank, clean_qos_class,
                                maybe_qos, parse_weights, request_class)
from lmrs_tpu.obs.ledger import CostLedger
from lmrs_tpu.obs.metrics import MetricsRegistry


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


def _cfg(**kw) -> EngineConfig:
    base = dict(backend="jax", scheduler="continuous", max_tokens=16,
                max_batch_slots=2, seed=0, decode_block=3,
                prefill_chunk=64, retry_delay=0.0)
    base.update(kw)
    return EngineConfig(**base)


class _Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _req(rid: int, tenant=None, klass=None, prompt="x") -> GenerationRequest:
    return GenerationRequest(prompt=prompt, request_id=rid,
                             temperature=0.0, max_new_tokens=8,
                             tenant=tenant, qos_class=klass)


# ------------------------------------------------------------ policy units


def test_parse_weights_drops_malformed_entries():
    out = parse_weights(["gold:4", "silver:0.5", "junk", "bad:-1",
                         "nan:x", ":2", "zero:0"])
    assert out == {"gold": 4.0, "silver": 0.5}


def test_clean_qos_class_and_ranks():
    assert clean_qos_class(" Batch ") == "batch"
    assert clean_qos_class("INTERACTIVE") == "interactive"
    assert clean_qos_class("weird") is None
    assert clean_qos_class(7) is None
    assert class_rank(None) == 0 and class_rank("batch") == 1
    # unlabeled / dict-shaped requests degrade to interactive, never crash
    assert request_class(object()) == "interactive"
    assert request_class(_req(0, klass="batch")) == "batch"


def test_window_usage_expires_off_the_left_edge(monkeypatch):
    monkeypatch.setenv("LMRS_QOS_WINDOW_S", "10")
    clk = _Clock()
    pol = QoSPolicy(enabled=True, clock=clk)
    pol.note_usage([("a", 5.0)])
    clk.t += 5
    pol.note_usage([("b", 1.0), ("a", 0.0)])  # zero-cost events dropped
    assert pol.normalized_usage("a") == 5.0
    clk.t += 6  # a's event is now 11s old, past the 10s window
    assert pol.normalized_usage("a") == 0.0
    assert set(pol.report()["tenants"]) == {"b"}


def test_pick_index_class_then_deficit_then_fifo(monkeypatch):
    monkeypatch.setenv("LMRS_QOS_WEIGHTS", "heavy:10")
    clk = _Clock()
    reg = MetricsRegistry()
    pol = QoSPolicy(reg, enabled=True, clock=clk)
    pol.note_usage([("noisy", 10.0), ("quiet", 1.0), ("heavy", 20.0)])
    # class outranks any deficit: the only interactive entry wins even
    # though its tenant burned more than the batch ones
    reqs = [_req(0, "quiet", "batch"), _req(1, "noisy", "batch"),
            _req(2, "noisy", "interactive")]
    assert pol.pick_index(reqs) == 2
    # within one class the lowest normalized usage wins (20/10 < 10/1)
    reqs = [_req(0, "noisy", "batch"), _req(1, "heavy", "batch")]
    assert pol.pick_index(reqs) == 1
    # full tie (same tenant, same class) degrades to FIFO
    reqs = [_req(0, "noisy", "batch"), _req(1, "noisy", "batch")]
    assert pol.pick_index(reqs) == 0
    # every non-head pick above incremented the reorder counter
    assert reg.counter("lmrs_qos_reorders_total").value == 2.0
    assert reg.gauge("lmrs_qos_window_device_seconds").value == 31.0


def test_victim_key_targets_over_quota_bulk_first():
    clk = _Clock()
    pol = QoSPolicy(enabled=True, clock=clk)
    pol.note_usage([("noisy", 10.0), ("quiet", 1.0)])
    rows = [(_req(0, "quiet", "interactive"), 1.0),
            (_req(1, "quiet", "batch"), 2.0),
            (_req(2, "noisy", "batch"), 3.0),
            (_req(3, "noisy", "batch"), 4.0)]
    ranked = sorted(rows, key=lambda r: pol.victim_key(r[0], r[1]))
    # victim = max key: the YOUNGEST over-quota batch row; the
    # interactive row is the safest slot in the pool
    assert ranked[-1][0].request_id == 3
    assert ranked[0][0].request_id == 0


def test_over_quota_is_self_normalizing():
    clk = _Clock()
    pol = QoSPolicy(enabled=True, clock=clk)
    pol.weights = {"gold": 3.0}
    # a lone tenant is never over quota (its fair share is 100%)
    pol.note_usage([("solo", 100.0)])
    assert not pol.over_quota("solo")
    pol = QoSPolicy(enabled=True, clock=clk)
    pol.weights = {"gold": 3.0}
    pol.note_usage([("gold", 70.0), ("base", 30.0)])
    # gold's fair share of the 100s window is 75 (weight 3 of 4): under;
    # base's is 25: over
    assert not pol.over_quota("gold")
    assert pol.over_quota("base")
    rep = pol.report()
    assert rep["tenants"]["base"]["over_quota"] is True
    assert rep["tenants"]["gold"]["over_quota"] is False
    assert rep["tenants"]["gold"]["fair_share"] == 0.75


def test_maybe_qos_kill_switch(monkeypatch):
    monkeypatch.setenv("LMRS_QOS", "0")
    assert maybe_qos() is None
    monkeypatch.setenv("LMRS_QOS", "1")
    pol = maybe_qos()
    assert pol is not None and pol.enabled
    rep = pol.report()
    assert rep["object"] == "qos" and rep["enabled"] is True
    assert set(rep) == {"object", "enabled", "window_s",
                        "window_device_seconds", "classes", "tenants"}


def test_preempt_counter(monkeypatch):
    reg = MetricsRegistry()
    pol = QoSPolicy(reg, enabled=True, clock=_Clock())
    pol.note_preempt()
    pol.note_preempt()
    assert reg.counter("lmrs_qos_preempt_victims_total").value == 2.0


# --------------------------------------------------- ledger observer hooks


def test_ledger_observer_receives_conserved_pairs():
    led = CostLedger(enabled=True)
    captured: list[list] = []
    led.observer = lambda pairs: captured.append(list(pairs))
    ra, rb = _req(0, "a"), _req(1, "b")
    led.note_step(0.2, decode_rows=[(ra, 3, 1), (rb, 5, 1)])
    assert led.audit() == []
    total = sum(s for batch in captured for _, s in batch)
    assert abs(total - 0.2) < 1e-9
    assert {t for batch in captured for t, _ in batch} == {"a", "b"}


def test_overflow_counter_and_warn_once(monkeypatch, caplog):
    """Regression for the lmrs_cost_tenants_overflow_total counter: past
    LMRS_COST_TENANTS_MAX each folded FINISH increments it, and the
    cardinality warning fires exactly once."""
    monkeypatch.setenv("LMRS_COST_TENANTS_MAX", "1")
    reg = MetricsRegistry()
    led = CostLedger(reg, enabled=True)
    with caplog.at_level(logging.WARNING):
        for i, tenant in enumerate(("a", "b", "c")):
            r = _req(i, tenant)
            led.note_step(0.25, decode_rows=[(r, 2, 1)])
            led.finish(r, GenerationResult(request_id=i,
                                           completion_tokens=2,
                                           prompt_tokens=1))
    assert reg.counter("lmrs_cost_tenants_overflow_total").value == 2.0
    assert caplog.text.count("cardinality cap") == 1
    assert led.audit() == []
    doc = led.usage_report()
    assert set(doc["tenants"]) == {"a", "other"}
    assert doc["tenants"]["other"]["requests"] == 2


# ------------------------------------------------- mock admission ordering


def _gate_order(qos_on: bool) -> tuple[list[str], dict]:
    """Fill a slots=1 MockEngine's admission queue in a deterministic
    arrival order while the only slot is held, then release and record
    completion order (slot serialization makes it the admission order)."""
    eng = MockEngine(seed=0, latency_s=0.03, slots=1, qos=qos_on)
    blocker = _req(99, "noisy", "batch", prompt="blocker")
    eng._admit_wait(blocker)  # occupy the only slot
    if qos_on:
        assert eng.qos is not None
        eng.qos.note_usage([("noisy", 5.0)])
    else:
        assert eng.qos is None
    done: list[str] = []
    done_lock = threading.Lock()

    def run(tag: str, tenant: str, klass: str, rid: int) -> None:
        res = eng.generate_batch([_req(rid, tenant, klass,
                                       prompt=f"req {tag}")])[0]
        assert res.error is None, res.error
        with done_lock:
            done.append(tag)

    waiters = [("b0", "noisy", "batch"), ("b1", "noisy", "batch"),
               ("quiet", "quiet", "interactive"), ("b2", "noisy", "batch")]
    threads = []
    for i, (tag, tenant, klass) in enumerate(waiters):
        t = threading.Thread(target=run, args=(tag, tenant, klass, i),
                             daemon=True)
        t.start()
        deadline = time.time() + 10.0
        while time.time() < deadline:
            with eng._adm_cv:
                if len(eng._adm_queue) == i + 1:
                    break
            time.sleep(0.005)
        threads.append(t)
    eng._admit_release()  # free the slot: admission begins
    for t in threads:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in threads)
    u = eng.ledger.usage_report()
    assert u["live_requests"] == 0
    return done, eng.qos_report()


def test_mock_gate_qos_admits_interactive_first():
    done, rep = _gate_order(qos_on=True)
    # the interactive waiter jumps the flooded queue; the batch waiters
    # keep their FIFO order among themselves
    assert done == ["quiet", "b0", "b1", "b2"]
    assert rep["enabled"] is True


def test_mock_gate_disarmed_is_strict_fifo():
    done, rep = _gate_order(qos_on=False)
    assert done == ["b0", "b1", "quiet", "b2"]
    assert rep == {"object": "qos", "enabled": False}


def test_tenant_stamp_rollups_conserve_under_concurrent_gate():
    """Concurrent TenantStampEngine facades (the job/session billing
    path) through one slot-limited gate: every facade's rollup counts
    its own requests exactly and the shared ledger conserves."""
    eng = MockEngine(seed=0, latency_s=0.005, slots=1)
    assert eng.qos is not None
    facades = {
        "job-a": TenantStampEngine(eng, "job-a", qos_class="batch"),
        "job-b": TenantStampEngine(eng, "job-b", qos_class="batch"),
        "live": TenantStampEngine(eng, "live", qos_class="interactive"),
    }
    n = 6
    errors: list[str] = []

    def run(k: int, name: str, fac: TenantStampEngine) -> None:
        try:
            for i in range(n):
                res = fac.generate_batch([GenerationRequest(
                    prompt=f"{name} chunk {i} with enough words to bill",
                    request_id=k * 1000 + i, temperature=0.0,
                    max_new_tokens=8)])[0]
                assert res.error is None, res.error
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(f"{name}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=run, args=(k, name, fac),
                                daemon=True)
               for k, (name, fac) in enumerate(facades.items())]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60.0)
    assert not errors and not any(t.is_alive() for t in threads)
    for name, fac in facades.items():
        assert fac.usage_rollup.get("requests") == n, name
    doc = eng.ledger.usage_report()
    assert eng.ledger.audit() == []
    assert doc["live_requests"] == 0
    assert {t: r["requests"] for t, r in doc["tenants"].items()} == {
        "job-a": n, "job-b": n, "live": n}
    tenant_dev = sum(r["device_seconds"] for r in doc["tenants"].values())
    assert abs(tenant_dev - doc["totals"]["device_seconds"]) < 1e-9


# ------------------------------------------- scheduler kill-switch parity


def test_scheduler_qos_kill_switch_token_identity(monkeypatch):
    """LMRS_QOS=0 vs 1 on the continuous scheduler: greedy outputs are
    byte-identical (the policy reorders admission and preemption order,
    never any request's tokens) and conservation holds in both arms."""
    from lmrs_tpu.engine.jax_engine import JaxEngine

    def reqs():
        pre = "shared qos preamble alpha beta "
        return [GenerationRequest(
            prompt=(pre if i % 2 else "") + f"request {i} "
            + "lorem ipsum dolor sit amet " * (1 + 3 * (i % 2)),
            request_id=i, temperature=0.0, max_new_tokens=10 + i,
            tenant=("bulk" if i % 2 else "live"),
            qos_class=("batch" if i % 2 else "interactive"))
            for i in range(4)]

    def run():
        eng = JaxEngine(_cfg(), tiny_model())
        out = eng.generate_batch(reqs())
        assert eng._scheduler.audit() == []
        texts = [(r.text, r.finish_reason, r.completion_tokens)
                 for r in out]
        rep = eng.qos_report()
        eng.shutdown()
        return texts, rep

    monkeypatch.setenv("LMRS_QOS", "0")
    texts_off, rep_off = run()
    assert rep_off == {"object": "qos", "enabled": False}
    monkeypatch.setenv("LMRS_QOS", "1")
    texts_on, rep_on = run()
    assert rep_on["enabled"] is True
    assert texts_on == texts_off


# --------------------------------------------------- server-tier surfaces


def _post(port, body, headers=None):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("POST", "/v1/chat/completions", json.dumps(body),
              {"Content-Type": "application/json", **(headers or {})})
    r = c.getresponse()
    out = json.loads(r.read())
    c.close()
    return r.status, out


def _get(port, path):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("GET", path)
    r = c.getresponse()
    out = json.loads(r.read())
    c.close()
    return r.status, out


def _chat_body(text="summarize this deterministic transcript please"):
    return {"messages": [{"role": "user", "content": text}],
            "max_tokens": 16}


def test_server_mints_default_tenant_for_anonymous_ingress():
    """Ingress without X-LMRS-Tenant bills to the minted ``default``
    tenant (SERVING.md): anonymous traffic is visible in fair-share and
    chargeback instead of invisible."""
    from lmrs_tpu.serving.server import EngineHTTPServer

    srv = EngineHTTPServer(MockEngine(seed=0), port=0)
    srv.start_background()
    try:
        st, out = _post(srv.port, _chat_body())
        assert st == 200
        assert out["usage"]["cost"]["tenant"] == "default"
        st, out = _post(srv.port, _chat_body(),
                        headers={"X-LMRS-Tenant": "acme"})
        assert st == 200 and out["usage"]["cost"]["tenant"] == "acme"
        st, u = _get(srv.port, "/v1/usage")
        assert st == 200 and set(u["tenants"]) == {"default", "acme"}
    finally:
        srv.shutdown()


def test_usage_qos_block_wire_parity(monkeypatch):
    """GET /v1/usage carries the qos block only while armed — with
    LMRS_QOS=0 the key is ABSENT (byte parity), not enabled:false."""
    from lmrs_tpu.serving.server import EngineHTTPServer

    def run():
        srv = EngineHTTPServer(MockEngine(seed=0, latency_s=0.01), port=0)
        srv.start_background()
        try:
            st, _ = _post(srv.port, _chat_body(),
                          headers={"X-LMRS-Tenant": "acme"})
            assert st == 200
            return _get(srv.port, "/v1/usage")[1]
        finally:
            srv.shutdown()

    monkeypatch.setenv("LMRS_QOS", "1")
    on = run()
    monkeypatch.setenv("LMRS_QOS", "0")
    off = run()
    assert on["qos"]["enabled"] is True and "tenants" in on["qos"]
    assert "qos" not in off


def test_batcher_wave_order_follows_policy(monkeypatch):
    """The micro-batcher's wave order: identity (FIFO) when the engine
    carries no policy, repeated fair-share picks when armed."""
    from lmrs_tpu.serving.server import _Batcher

    class _J:
        def __init__(self, req):
            self.request = req

    def jobs():
        return [_J(_req(0, "noisy", "batch")),
                _J(_req(1, "noisy", "batch")),
                _J(_req(2, "quiet", "interactive"))]

    monkeypatch.setenv("LMRS_QOS", "0")
    b = _Batcher(MockEngine(seed=0))
    try:
        js = jobs()
        assert b._qos_order(js) is js  # disarmed: the very same list
    finally:
        b.shutdown()
    monkeypatch.setenv("LMRS_QOS", "1")
    b = _Batcher(MockEngine(seed=0))
    try:
        b.engine.qos.note_usage([("noisy", 10.0)])
        js = jobs()
        out = b._qos_order(js)
        assert [j.request.request_id for j in out] == [2, 0, 1]
    finally:
        b.shutdown()


# ------------------------------------------------- router fleet elasticity


def test_router_fleet_elasticity_api():
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1", "h2:2"], timeout_s=1.0)
    try:
        h3 = router.add_host("h3:3")
        assert len(router.hosts) == 3
        assert router.add_host("h3:3") is h3  # idempotent by netloc
        assert len(router.hosts) == 3
        assert router.drain_host("h3:3") is True
        assert not h3.healthy  # draining leaves the dispatch order
        assert router.drain_host("nope:9") is False
        assert router.add_host("h3:3") is h3  # re-add clears the drain
        assert h3.healthy
        router.drain_host("h3:3")
        h3.note_leg(+1)
        assert router.host_idle("h3:3") is False
        assert router.remove_host("h3:3") is False  # legs still in flight
        assert router.remove_host("h3:3", force=True) is True
        assert len(router.hosts) == 2
        # removal purges the tenant-affinity map
        req = _req(0, "acme")
        router._note_tenant_host(req, router.hosts[1])
        assert router.remove_host("h2:2") is True
        with router._stats_lock:
            assert "acme" not in router._tenant_hosts
        # the last host can never be removed
        assert router.remove_host("h1:1", force=True) is False
        assert router.remove_host("ghost:0") is False
    finally:
        router.shutdown()


def test_router_tenant_affinity_lru_and_slo_gating():
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1", "h2:2"], timeout_s=1.0)
    try:
        req = _req(0, "acme")
        assert router._tenant_pref(req, "full") is None  # no history yet
        router._note_tenant_host(req, router.hosts[0])
        assert router._tenant_pref(req, "full") is router.hosts[0]
        assert router._tenant_routed == 1
        # draining PURGES the tenant pin (ISSUE 20: stale affinity must
        # not keep steering warm traffic at a host on its way out) — a
        # re-added host earns stickiness back on its next serve
        router.drain_host("h1:1")
        assert router._tenant_pref(req, "full") is None
        router.add_host("h1:1")
        assert router._tenant_pref(req, "full") is None
        router._note_tenant_host(req, router.hosts[0])
        assert router._tenant_pref(req, "full") is router.hosts[0]
        # anonymous requests never stick
        assert router._tenant_pref(_req(1), "full") is None
        # bounded LRU: oldest entry evicts past the cap, re-insert
        # refreshes recency
        router._tenant_hosts_max = 2
        for i, t in enumerate(("t0", "t1", "t2")):
            router._note_tenant_host(_req(2 + i, t), router.hosts[0])
        with router._stats_lock:
            assert set(router._tenant_hosts) == {"t1", "t2"}
        router._note_tenant_host(_req(5, "t1"), router.hosts[1])
        router._note_tenant_host(_req(6, "t3"), router.hosts[0])
        with router._stats_lock:
            assert set(router._tenant_hosts) == {"t1", "t3"}
        # kill switch: no stickiness, no recording
        router.tenant_route = False
        assert router._tenant_pref(req, "full") is None
    finally:
        router.shutdown()


def test_router_drain_purges_sticky_caches(monkeypatch):
    """Drain must scrub EVERY sticky structure pointing at the draining
    host — tenant pins, job/session pins, summary rows — or stale
    affinity keeps steering warm traffic at a pod on its way out."""
    from lmrs_tpu.serving.router import RouterEngine

    monkeypatch.setenv("LMRS_KV_MIGRATE", "0")
    router = RouterEngine(["h1:1", "h2:2"], timeout_s=1.0)
    try:
        router._note_tenant_host(_req(0, "acme"), router.hosts[0])
        router._pin_job("job-1", "h1:1")
        router._pin_job("sess-1", "h1:1")
        router._pin_job("keep", "h2:2")
        with router._summary_lock:
            router._summaries["h1:1"] = {"t": 0.0, "map": {}}
        assert router.drain_host("h1:1")
        with router._stats_lock:
            assert "acme" not in router._tenant_hosts
        with router._job_lock:
            assert router._job_hosts == {"keep": "h2:2"}
        with router._summary_lock:
            assert "h1:1" not in router._summaries
        # kill switch: the purge happens, but no migration ever launches
        assert not router.migrations_pending("h1:1")
        assert router._kv_moves == 0 and router._kv_failures == 0
    finally:
        router.shutdown()


def test_router_drain_migration_repins_to_sibling():
    """Armed drain against unreachable hosts: zero page sets move (a
    dark pod has nothing to export), but the drained host's sticky pins
    still re-home onto the healthy sibling and the migration never
    wedges the drain."""
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1", "h2:2"], timeout_s=1.0)
    try:
        assert router.kv_migrate
        router._pin_job("sess-1", "h1:1")
        assert router.drain_host("h1:1")
        deadline = time.time() + 15.0
        while (router.migrations_pending("h1:1")
               and time.time() < deadline):
            time.sleep(0.02)
        assert not router.migrations_pending("h1:1")
        assert router._kv_moves == 0
        with router._job_lock:
            assert router._job_hosts.get("sess-1") == "h2:2"
    finally:
        router.shutdown()


def test_router_forced_remove_purges_pins_and_prefetch_marks():
    """A FORCED remove (breaker-dead pod, no drain) must not leave job
    pins or prefetch dedup marks aimed at a host that is gone."""
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1", "h2:2"], timeout_s=1.0)
    try:
        router._pin_job("j", "h2:2")
        with router._kv_lock:
            router._kv_prefetched[("h2:2", "k")] = 0.0
            router._kv_prefetched[("h1:1", "k")] = 0.0
        assert router.remove_host("h2:2", force=True)
        with router._job_lock:
            assert "j" not in router._job_hosts
        with router._kv_lock:
            assert ("h2:2", "k") not in router._kv_prefetched
            assert ("h1:1", "k") in router._kv_prefetched
    finally:
        router.shutdown()


# ---------------------------------------------------- autoscaler control


def test_autoscaler_scales_up_on_burn_with_cooldown():
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1"], timeout_s=1.0)
    try:
        router._slo_penalty = lambda h: 1  # every healthy host burning
        clk = _Clock()
        reg = MetricsRegistry()
        seq = itertools.count()
        a = Autoscaler(router, lambda: f"up{next(seq)}:9001",
                       clock=clk, registry=reg, enabled=True,
                       interval_s=1.0, min_hosts=1, max_hosts=3,
                       cooldown_ticks=2, drain_timeout_s=10.0)
        s1 = a.tick()
        assert any(x.startswith("spawned:") for x in s1["actions"])
        assert len(router.hosts) == 2
        clk.t += 1
        assert a.tick()["actions"] == []  # cooldown paces the staircase
        clk.t += 1
        a.tick()
        assert len(router.hosts) == 3
        for _ in range(3):  # at max_hosts: no further spawns
            clk.t += 1
            a.tick()
        assert len(router.hosts) == 3
        assert reg.counter("lmrs_autoscale_scale_ups_total").value == 2.0
        assert reg.gauge("lmrs_autoscale_pool_size").value == 3.0
        rep = a.report()
        assert rep["pool"] == 3 and len(rep["spawned"]) == 2
    finally:
        router.shutdown()


def test_autoscaler_drains_then_removes_idle_spawned_host():
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1"], timeout_s=1.0)
    try:
        router._slo_penalty = lambda h: 1
        clk = _Clock()
        reg = MetricsRegistry()
        removed: list[str] = []
        a = Autoscaler(router, lambda: "up0:9001",
                       remove_cb=removed.append, clock=clk, registry=reg,
                       enabled=True, interval_s=1.0, min_hosts=1,
                       max_hosts=2, cooldown_ticks=1, drain_timeout_s=5.0)
        a.tick()  # burn -> spawn
        assert len(router.hosts) == 2
        router._slo_penalty = lambda h: 0  # burn clears, traffic idles
        clk.t += 1
        s = a.tick()
        assert s["actions"] == ["draining:up0:9001"]
        assert next(h for h in router.hosts
                    if h.netloc == "up0:9001").draining
        # the drain kicked a background KV migration (unreachable hosts:
        # it finishes empty); the advance tick holds until it clears
        deadline = time.time() + 15.0
        while (router.migrations_pending("up0:9001")
               and time.time() < deadline):
            time.sleep(0.02)
        clk.t += 1
        s = a.tick()
        assert s["actions"] == ["removed:up0:9001"]
        assert len(router.hosts) == 1 and removed == ["up0:9001"]
        assert reg.counter("lmrs_autoscale_drains_total").value == 1.0
        assert reg.counter("lmrs_autoscale_scale_downs_total").value == 1.0
        # at min_hosts nothing further shrinks
        clk.t += 1
        assert a.tick()["actions"] == []
        assert len(router.hosts) == 1
    finally:
        router.shutdown()


def test_autoscaler_holds_removal_while_kv_migrates():
    """An idle drained host is NOT removed while its KV migration is in
    flight (pages must not be torn off a pod mid-copy); the drain
    timeout still backstops a wedged migration."""
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1"], timeout_s=1.0)
    try:
        router._slo_penalty = lambda h: 1
        clk = _Clock()
        a = Autoscaler(router, lambda: "up0:9001", clock=clk,
                       enabled=True, interval_s=1.0, min_hosts=1,
                       max_hosts=2, cooldown_ticks=1, drain_timeout_s=4.0)
        a.tick()
        router._slo_penalty = lambda h: 0
        clk.t += 1
        assert a.tick()["actions"] == ["draining:up0:9001"]
        router.migrations_pending = lambda n: n == "up0:9001"  # wedged copy
        clk.t += 1
        s = a.tick()  # idle, but mid-migration: the drain holds
        assert not any(x.startswith("removed") for x in s["actions"])
        assert len(router.hosts) == 2
        clk.t += 5  # past drain_timeout_s: the backstop removes anyway
        assert a.tick()["actions"] == ["removed:up0:9001"]
        assert len(router.hosts) == 1
    finally:
        router.shutdown()


def test_autoscaler_force_removes_wedged_drain():
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1"], timeout_s=1.0)
    try:
        router._slo_penalty = lambda h: 1
        clk = _Clock()
        a = Autoscaler(router, lambda: "up0:9001", clock=clk,
                       enabled=True, interval_s=1.0, min_hosts=1,
                       max_hosts=2, cooldown_ticks=1, drain_timeout_s=3.0)
        a.tick()
        router._slo_penalty = lambda h: 0
        clk.t += 1
        assert a.tick()["actions"] == ["draining:up0:9001"]
        victim = next(h for h in router.hosts if h.netloc == "up0:9001")
        victim.note_leg(+1)  # a leg that never finishes
        clk.t += 1
        s = a.tick()  # not idle, inside the timeout: drain holds
        assert not any(x.startswith("removed") for x in s["actions"])
        assert len(router.hosts) == 2
        clk.t += 5  # past drain_timeout_s: the wedged victim cannot
        s = a.tick()  # pin the loop forever
        assert s["actions"] == ["removed:up0:9001:forced"]
        assert len(router.hosts) == 1
    finally:
        router.shutdown()


def test_autoscaler_never_drains_operator_capacity():
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1", "h2:2"], timeout_s=1.0)
    try:
        clk = _Clock()
        a = Autoscaler(router, lambda: None, clock=clk, enabled=True,
                       interval_s=1.0, min_hosts=1, max_hosts=4,
                       cooldown_ticks=1)
        a.tick()
        clk.t += 1
        s = a.tick()  # idle + size > min, but neither host was spawned
        assert s["actions"] == [] and len(router.hosts) == 2
    finally:
        router.shutdown()


def test_autoscaler_kill_switch(monkeypatch):
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1"], timeout_s=1.0)
    try:
        monkeypatch.delenv("LMRS_AUTOSCALE", raising=False)
        assert maybe_autoscaler(router, lambda: None) is None  # default OFF
        monkeypatch.setenv("LMRS_AUTOSCALE", "0")
        assert maybe_autoscaler(router, lambda: None) is None
        monkeypatch.setenv("LMRS_AUTOSCALE", "1")
        a = maybe_autoscaler(router, lambda: None)
        assert a is not None and a.enabled
        # a disabled instance observes but never acts, even under burn
        router._slo_penalty = lambda h: 2
        off = Autoscaler(router, lambda: "up0:9001", clock=_Clock(),
                         enabled=False, interval_s=1.0, min_hosts=1,
                         max_hosts=4)
        s = off.tick()
        assert s == {"enabled": False, "pool": 1, "actions": []}
        assert len(router.hosts) == 1
    finally:
        router.shutdown()
