"""Hang-survival tier (ISSUE 14): dispatch watchdog, straggler hedging,
circuit-breaker quarantine, and supervised restart.

Every scenario drives ``action: "stall"`` plans (or real non-answering
sockets) through the new fault sites — ``scheduler.heartbeat``,
``replicated.shard``, ``router.hedge`` — and asserts the system-level
contract: a wedge becomes a BOUNDED, observable failure (wedged/deadline
results, postmortem, quarantine, respawn) instead of a silent freeze,
and ``LMRS_WATCHDOG=0`` restores the pre-watchdog inline dispatch
exactly.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from lmrs_tpu.config import EngineConfig, MeshConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest, GenerationResult
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.engine.replicated import ReplicatedEngine
from lmrs_tpu.testing import faults
from lmrs_tpu.testing.faults import FaultPlan

sys.path.insert(0, os.path.dirname(__file__))
import _job_worker as jw  # noqa: E402 - shared job transcript builder

TINY = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                   n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                   dtype="float32")

ECFG = EngineConfig(backend="jax", scheduler="continuous", max_tokens=64,
                    max_batch_slots=2, seed=0, decode_block=4,
                    page_size=16, num_pages=20)


def _req(rid: int, prompt: str = "hang survival probe alpha bravo",
         max_new: int = 8, deadline_s: float | None = None):
    return GenerationRequest(prompt=prompt, request_id=rid,
                             temperature=0.0, max_new_tokens=max_new,
                             deadline_s=deadline_s)


def _stall_plan(occ: int, stall_s: float) -> FaultPlan:
    return FaultPlan(faults=[{"site": "scheduler.heartbeat", "at": [occ],
                              "action": "stall", "stall_s": stall_s}])


@pytest.fixture(scope="module")
def wd_engine():
    eng = JaxEngine(ECFG, TINY)
    # warm the compiled shapes AND the step-time EMA so the explicit tiny
    # LMRS_WATCHDOG_S thresholds below are the only gate (cold compiles
    # run under the watchdog's grace window and must not be part of the
    # scenario timing)
    for rid in (990, 991):
        eng.generate_batch([_req(rid, prompt="warmup wedge probe")])
    yield eng
    eng.shutdown()


# ------------------------------------------------------------- watchdog


def test_watchdog_ema_ignores_graced_windows():
    """A cold-compile wall must NOT fold into the step-time EMA even
    though grace_end() re-arms stall detection the moment the compile
    lands — folding it would inflate the auto wedge threshold ~30x per
    compile for the rest of the run."""
    from lmrs_tpu.engine.watchdog import DispatchWatchdog

    wd = DispatchWatchdog()
    wd.run_started()
    time.sleep(0.01)
    wd.beat()
    ema1 = wd.ema_step_s
    assert ema1 is not None
    wd.grace_cold()   # a "compile" opens...
    wd.grace_end()    # ...and lands: detection re-armed
    assert wd.stalled_for() >= 0.0  # no grace suppression left
    time.sleep(0.08)  # the compile-polluted window
    wd.beat()
    assert wd.ema_step_s == ema1, "graced window folded into the EMA"
    time.sleep(0.01)
    wd.beat()  # the next CLEAN window folds again
    assert wd.ema_step_s != ema1


def test_watchdog_armed_by_default(wd_engine):
    """LMRS_WATCHDOG defaults on: the runner thread exists, the scheduler
    carries a heartbeat, and a plain batch behaves exactly as before."""
    assert wd_engine._runner is not None
    assert wd_engine._scheduler.watchdog is not None
    assert wd_engine._scheduler.watchdog.ema_step_s is not None
    assert not wd_engine.wedged()


def test_wedge_mid_decode_bounded_wedged_results(wd_engine, monkeypatch,
                                                 tmp_path):
    """The tentpole scenario: a stall wedges the dispatch loop mid-decode.
    Within a bounded wall the watchdog declares the wedge — flight
    recorder postmortem written, in-flight requests terminate
    ``finish_reason="wedged"`` with the error marked, the engine runs
    fail-fast degraded — and once the stall ends the abandoned run
    recovers the engine with the auditor clean."""
    monkeypatch.setenv("LMRS_WATCHDOG_S", "0.3")
    monkeypatch.setenv("LMRS_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.setenv("LMRS_POSTMORTEM_MIN_S", "0")
    sched = wd_engine._scheduler
    fires = sched.metrics["watchdog_fires"]
    t0 = time.time()
    # occurrence 3: the loop has already dispatched (mid-run), so the
    # wedge lands while requests hold slots
    with faults.injected(_stall_plan(3, 2.5)):
        out = wd_engine.generate_batch([_req(0), _req(1)])
    wall = time.time() - t0
    assert wall < 2.0, f"wedge delivery not bounded: {wall:.2f}s"
    assert [r.finish_reason for r in out] == ["wedged", "wedged"]
    assert all(r.error and "wedged" in r.error for r in out)
    assert sched.metrics["watchdog_fires"] == fires + 1
    assert sched.metrics["wedged_requests"] >= 2
    assert wd_engine.wedged()
    # fail-fast while degraded: nothing queues behind the dead dispatch
    t0 = time.time()
    ff = wd_engine.generate_batch([_req(2)])[0]
    assert ff.finish_reason == "wedged" and time.time() - t0 < 0.5
    # postmortem: schema-valid, reason "watchdog"
    from lmrs_tpu.obs import validate_postmortem_file

    dumps = sorted(tmp_path.glob("postmortem-watchdog-*.json"))
    assert dumps, "watchdog fired no postmortem"
    doc = validate_postmortem_file(dumps[0])
    assert doc["reason"] == "watchdog"
    assert doc["extra"]["stalled_s"] >= 0.3
    # the stall ends; the abandoned run finishes and the engine re-arms
    assert wd_engine._runner.wait_idle(30.0)
    assert not wd_engine.wedged()
    good = wd_engine.generate_batch([_req(3)])[0]
    assert good.finish_reason in ("stop", "length") and good.error is None
    assert sched.audit() == []


def test_wedged_run_expired_deadlines_deliver_deadline(wd_engine,
                                                       monkeypatch):
    """Satellite: deadline-expired in-flight requests used to be swept
    only at block boundaries a wedged loop never reaches — the watchdog
    sweep delivers their contractual ``"deadline"`` results (no error;
    the executor must not retry an expired budget)."""
    monkeypatch.setenv("LMRS_WATCHDOG_S", "0.6")
    dl_before = wd_engine._scheduler.metrics["deadline_exceeded"]
    with faults.injected(_stall_plan(1, 2.0)):
        out = wd_engine.generate_batch(
            [_req(10, deadline_s=time.time() + 0.4, max_new=32)])
    assert out[0].finish_reason == "deadline", out[0]
    assert out[0].error is None
    assert (wd_engine._scheduler.metrics["deadline_exceeded"]
            == dl_before + 1)
    assert wd_engine._runner.wait_idle(30.0)
    assert wd_engine._scheduler.audit() == []


def test_watchdog_off_is_inline_and_token_identical(wd_engine, monkeypatch):
    """The kill switch: LMRS_WATCHDOG=0 builds no runner and no watchdog
    — dispatch runs inline on the caller thread (today's path) — and a
    heartbeat stall plan simply stalls the run, which then completes
    normally, token-identical to the armed engine's fault-free output."""
    want = wd_engine.generate_batch([_req(20)])[0]
    if wd_engine._runner is not None:  # None when CI re-runs this test
        assert wd_engine._runner.wait_idle(5.0)  # with LMRS_WATCHDOG=0
    monkeypatch.setenv("LMRS_WATCHDOG", "0")
    eng = JaxEngine(ECFG, TINY)
    try:
        assert eng._runner is None
        assert eng._scheduler.watchdog is None
        t0 = time.time()
        with faults.injected(_stall_plan(1, 0.7)):
            got = eng.generate_batch([_req(20)])[0]
        assert time.time() - t0 >= 0.7  # the stall really blocked the run
        assert got.finish_reason == want.finish_reason
        assert got.text == want.text
        assert eng._scheduler.metrics["watchdog_fires"] == 0
        assert eng._scheduler.audit() == []
    finally:
        eng.shutdown()


def test_executor_retry_completes_after_transient_wedge(wd_engine,
                                                        monkeypatch):
    """Acceptance: a deterministic stall plan at scheduler.heartbeat
    completes a workload with bounded wall time and outputs
    token-identical to a fault-free run — the wedged results carry an
    error, the executor retries once the transient stall clears."""
    from lmrs_tpu.engine.executor import MapExecutor

    monkeypatch.setenv("LMRS_WATCHDOG_S", "0.3")
    reqs = [_req(i, prompt=f"retry after wedge {i}") for i in range(3)]
    # retry_delay outlasts the stall AND the abandoned run's drain (it
    # keeps computing the workload after the stall clears, and the
    # engine stays fail-fast degraded until it finishes)
    ex = MapExecutor(wd_engine, EngineConfig(retry_attempts=3,
                                             retry_delay=2.5))
    baseline = [(r.request_id, r.text) for r in ex.run_requests(reqs)]
    assert wd_engine._runner.wait_idle(10.0)
    t0 = time.time()
    with faults.injected(_stall_plan(2, 1.0)):
        out = ex.run_requests([_req(i, prompt=f"retry after wedge {i}")
                               for i in range(3)])
    assert time.time() - t0 < 20.0
    assert [(r.request_id, r.text) for r in out] == baseline
    assert all(r.error is None for r in out)
    assert wd_engine._runner.wait_idle(30.0)
    assert wd_engine._scheduler.audit() == []


# ------------------------------------------- replicated straggler containment


@pytest.fixture(scope="module")
def dp2():
    eng = ReplicatedEngine(
        EngineConfig(backend="jax", max_tokens=16, max_batch_slots=4,
                     retry_delay=0.0, seed=0, decode_block=4,
                     prefill_chunk=128, num_pages=64, page_size=16),
        ModelConfig(name="tiny-test", vocab_size=512, dim=64, n_layers=2,
                    n_heads=4, n_kv_heads=2, hidden_dim=128,
                    max_seq_len=512),
        MeshConfig(dp=2, tp=1))
    yield eng
    eng.shutdown()


def _wave_reqs(n: int = 4):
    return [GenerationRequest(prompt=f"shard wedge probe {i}",
                              request_id=i, temperature=0.0,
                              max_new_tokens=6) for i in range(n)]


def test_replica_pools_are_daemonized(dp2):
    """Satellite: a wedged shard/probe future must never pin interpreter
    exit — every per-replica worker thread is a daemon."""
    for pool in dp2._pools:
        assert pool._thread.daemon


def test_wedged_shard_redispatches_token_identical(dp2):
    """A replica whose engine watchdog declared a wedge returns wedged
    results: the wave quarantines it and re-dispatches its shard onto the
    healthy replica — outputs token-identical to an all-healthy wave
    (greedy, identical weights), nothing surfaces as an error."""
    baseline = [(r.request_id, r.text) for r in
                dp2.generate_batch(_wave_reqs())]
    dp2._healthy[:] = [True, True]
    victim = dp2.replicas[0]
    orig = victim.generate_batch
    seen: list[str] = []

    def wedgy(requests, on_result=None, on_tokens=None):
        seen.extend(r.prompt for r in requests)
        return [GenerationResult(request_id=r.request_id,
                                 finish_reason="wedged",
                                 error="synthetic wedge")
                for r in requests]

    victim.generate_batch = wedgy
    try:
        out = dp2.generate_batch(_wave_reqs())
    finally:
        victim.generate_batch = orig
    assert seen, "victim replica saw no shard"
    assert [(r.request_id, r.text) for r in out] == baseline
    assert all(r.error is None for r in out)
    assert dp2._healthy == [False, True]
    # re-admission through the existing probe loop: the victim answers
    # again, a wave's probe re-admits it
    deadline = time.time() + 10
    while time.time() < deadline and not dp2._healthy[0]:
        dp2.generate_batch([GenerationRequest(prompt="probe tick",
                                              request_id=900,
                                              temperature=0.0,
                                              max_new_tokens=2)])
        time.sleep(0.05)
    assert dp2._healthy == [True, True]


def test_stalled_shard_quarantined_and_redispatched(dp2, monkeypatch):
    """``replicated.shard`` stall: the shard's worker wedges, the bounded
    wait times out, the replica is quarantined onto a fresh daemon pool,
    and the shard's requests complete on the healthy replica —
    token-identical, no errors."""
    monkeypatch.setenv("LMRS_SHARD_TIMEOUT_S", "1")
    dp2._healthy[:] = [True, True]
    baseline = [(r.request_id, r.text) for r in
                dp2.generate_batch(_wave_reqs())]
    dp2._healthy[:] = [True, True]
    old_pools = list(dp2._pools)
    plan = FaultPlan(faults=[{"site": "replicated.shard", "at": [1],
                              "action": "stall", "stall_s": 3.0,
                              "max_fires": 1}])
    t0 = time.time()
    with faults.injected(plan):
        out = dp2.generate_batch(_wave_reqs())
    assert time.time() - t0 < 3.0, "bounded wait did not contain the stall"
    assert [(r.request_id, r.text) for r in out] == baseline
    assert all(r.error is None for r in out)
    assert False in dp2._healthy  # one replica quarantined
    victim = dp2._healthy.index(False)
    assert dp2._pools[victim] is not old_pools[victim], \
        "quarantine must abandon the wedged pool"
    # the stall drains; the probe loop re-admits the quarantined replica
    time.sleep(3.0)
    deadline = time.time() + 15
    while time.time() < deadline and not all(dp2._healthy):
        dp2.generate_batch([GenerationRequest(prompt="probe tick",
                                              request_id=901,
                                              temperature=0.0,
                                              max_new_tokens=2)])
        time.sleep(0.1)
    assert all(dp2._healthy), "probe never re-admitted the replica"


# --------------------------------------------------- router circuit breaker


def _mock_server(latency_s: float = 0.0):
    from lmrs_tpu.serving.server import EngineHTTPServer

    srv = EngineHTTPServer(MockEngine(latency_s=latency_s), port=0,
                           batch_window_s=0.01)
    srv.start_background()
    return srv


def _wedge_listener():
    """A backend that accepts TCP but never answers — the hung-chip
    signature a connect-phase health check cannot see."""
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(16)
    held: list[socket.socket] = []

    def acceptor():
        while True:
            try:
                held.append(lst.accept()[0])
            except OSError:
                return

    threading.Thread(target=acceptor, daemon=True).start()
    return lst, held


def test_breaker_opens_on_consecutive_timeouts(monkeypatch):
    """Requests into a wedged (accepting, never answering) backend time
    out; LMRS_BREAKER_FAILURES consecutive failures open the breaker and
    the host leaves the dispatch order even though its port still
    accepts connections."""
    from lmrs_tpu.serving.router import RouterEngine

    monkeypatch.setenv("LMRS_BREAKER_FAILURES", "2")
    good = _mock_server()
    lst, held = _wedge_listener()
    wport = lst.getsockname()[1]
    router = RouterEngine([f"127.0.0.1:{wport}",
                           f"127.0.0.1:{good.port}"], timeout_s=0.5)
    try:
        h = router.hosts[0]
        for i in range(3):
            out = router.generate_batch([_req(i)])
            assert out[0].error is None, out[0]  # failover covered it
        assert h.breaker_state == "open"
        assert h.breaker_opens >= 1
        assert not h.healthy
        m = router.engine_metrics()
        assert m["per_host"][0]["breaker"] == "open"
        prom = router.prometheus_metrics()
        assert "lmrs_router_breaker_state" in prom
    finally:
        router.shutdown()
        good.shutdown()
        lst.close()
        for s in held:
            s.close()


def test_breaker_half_open_canary_closes(monkeypatch):
    """Open → (cooldown) → half-open canary (one tiny golden request
    through the REAL request path) → closed.  A failed canary re-opens
    for another cooldown."""
    from lmrs_tpu.serving.router import RouterEngine

    monkeypatch.setenv("LMRS_BREAKER_FAILURES", "2")
    monkeypatch.setenv("LMRS_BREAKER_COOLDOWN_S", "0.2")
    srv = _mock_server()
    router = RouterEngine([f"127.0.0.1:{srv.port}"])
    try:
        h = router.hosts[0]
        h.note_failed()
        h.note_failed()
        assert h.breaker_state == "open" and not h.healthy
        # inside the cooldown: the recovery pass must not canary yet
        router._recover_host(h)
        assert h.breaker_state == "open"
        time.sleep(0.25)
        router._recover_host(h)  # half-open canary against the live server
        assert h.breaker_state == "closed" and h.healthy
        # failure arm: open it again, kill the server, the canary re-opens
        h.note_failed()
        h.note_failed()
        assert h.breaker_state == "open"
        srv.shutdown()
        time.sleep(0.25)
        assert h.breaker_due()
        assert h.canary() is False
        assert h.breaker_state == "open" and not h.healthy
    finally:
        router.shutdown()


def test_breaker_disabled_keeps_binary_bit(monkeypatch):
    """LMRS_BREAKER_FAILURES=0 disables the breaker: any number of
    failures never opens it, and ``healthy`` degrades only through the
    legacy connect-phase condemnation — the pre-breaker behavior."""
    from lmrs_tpu.serving.router import _Host

    monkeypatch.setenv("LMRS_BREAKER_FAILURES", "0")
    h = _Host("127.0.0.1:1")
    for _ in range(10):
        h.note_failed()
    assert h.breaker_state == "closed" and h.healthy
    h.healthy = False
    assert not h.healthy
    h.healthy = True
    assert h.healthy


# ------------------------------------------------------------ tail hedging


def test_hedge_duplicates_straggler_first_result_wins(monkeypatch):
    """LMRS_HEDGE_MS: the primary leg straggles (slow backend), the hedge
    leg lands on the fast sibling and wins; the result is the same text
    either host would produce (mock determinism), the loser is hung up,
    and the hedge counters advance."""
    from lmrs_tpu.serving.router import RouterEngine

    slow = _mock_server(latency_s=1.5)
    fast = _mock_server()
    router = RouterEngine([f"127.0.0.1:{slow.port}",
                           f"127.0.0.1:{fast.port}"])
    try:
        monkeypatch.setenv("LMRS_HEDGE_MS", "150")
        t0 = time.time()
        res = router.generate_batch(
            [_req(0, prompt="hedge race alpha bravo charlie")])[0]
        wall = time.time() - t0
        assert res.error is None and res.finish_reason == "stop"
        assert wall < 1.4, f"hedge did not beat the straggler: {wall:.2f}s"
        assert router._hedges == 1 and router._hedge_wins == 1
        m = router.engine_metrics()
        assert m["hedge"] == {"hedges": 1, "wins": 1}
        prom = router.prometheus_metrics()
        assert "lmrs_router_hedges_total" in prom
        assert "lmrs_router_hedge_wins_total" in prom
    finally:
        router.shutdown()
        slow.shutdown()
        fast.shutdown()


def test_hedge_fault_site_abandons_hedge(monkeypatch):
    """``router.hedge`` raise: the hedge launch is abandoned — hedging is
    an optimization — and the primary leg still completes alone."""
    from lmrs_tpu.serving.router import RouterEngine

    slow = _mock_server(latency_s=0.5)
    fast = _mock_server()
    router = RouterEngine([f"127.0.0.1:{slow.port}",
                           f"127.0.0.1:{fast.port}"])
    try:
        monkeypatch.setenv("LMRS_HEDGE_MS", "100")
        with faults.injected(FaultPlan(faults=[
                {"site": "router.hedge", "at": [1], "max_fires": 1}])):
            res = router.generate_batch([_req(0)])[0]
        assert res.error is None
        assert router._hedges == 0 and router._hedge_wins == 0
    finally:
        router.shutdown()
        slow.shutdown()
        fast.shutdown()


def test_hedge_keeps_failover_on_fast_primary_failure(monkeypatch):
    """Arming LMRS_HEDGE_MS must never trade away availability: a
    primary that fails FAST (dead port, before the hedge delay) still
    gets the sibling attempt — as a plain failover, not a hedge (no
    hedge counters) — matching the un-hedged targets[:2] contract."""
    from lmrs_tpu.serving.router import RouterEngine

    good = _mock_server()
    with socket.socket() as s:  # a port nobody listens on
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    router = RouterEngine([f"127.0.0.1:{dead_port}",
                           f"127.0.0.1:{good.port}"])
    try:
        monkeypatch.setenv("LMRS_HEDGE_MS", "500")
        res = router.generate_batch(
            [_req(0, prompt="failover under hedging")])[0]
        assert res.error is None and res.finish_reason == "stop"
        assert router._hedges == 0 and router._hedge_wins == 0
    finally:
        router.shutdown()
        good.shutdown()


def test_hedge_error_results_do_not_feed_breaker(monkeypatch):
    """_one_colocated parity: a backend-ANSWERED error result (the host
    served the request; the request itself failed) must not count toward
    the circuit breaker under hedging — otherwise a client sending
    deterministically-bad requests would evict healthy hosts."""
    from lmrs_tpu.serving.router import RouterEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    monkeypatch.setenv("LMRS_BREAKER_FAILURES", "2")
    monkeypatch.setenv("LMRS_HEDGE_MS", "50")
    srvs = [EngineHTTPServer(MockEngine(fail_pattern="boomtrigger"),
                             port=0, batch_window_s=0.01)
            for _ in range(2)]
    for s in srvs:
        s.start_background()
    router = RouterEngine([f"127.0.0.1:{s.port}" for s in srvs])
    try:
        for i in range(3):
            res = router.generate_batch(
                [_req(i, prompt="boomtrigger request")])[0]
            assert res.finish_reason == "error"
        for h in router.hosts:
            assert h.breaker_state == "closed" and h.healthy, h.netloc
    finally:
        router.shutdown()
        for s in srvs:
            s.shutdown()


def test_hedge_off_by_default(monkeypatch):
    """LMRS_HEDGE_MS unset: no hedging path runs at all (the kill-switch
    arm of the acceptance A/B)."""
    monkeypatch.delenv("LMRS_HEDGE_MS", raising=False)
    from lmrs_tpu.serving.router import RouterEngine

    srv = _mock_server(latency_s=0.3)
    router = RouterEngine([f"127.0.0.1:{srv.port}"])
    try:
        res = router.generate_batch([_req(0)])[0]
        assert res.error is None
        assert router._hedges == 0
    finally:
        router.shutdown()
        srv.shutdown()


# --------------------------------------------------------- supervised restart


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _http(method: str, url: str, body: dict | None = None,
          timeout: float = 30.0):
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read() or b"{}")


def test_supervised_sigkill_respawn_resumes_job_token_identical(tmp_path):
    """Acceptance scenario, layer 4: ``lmrs-serve --supervise`` runs the
    engine in a child process; SIGKILLing the child mid-map makes the
    supervisor respawn it, the replacement's startup recovery resumes the
    job from the WAL, and the final summary is token-identical to an
    uninterrupted run of the same (transcript, params)."""
    from lmrs_tpu.jobs import journal as jl
    from lmrs_tpu.serving.server import EngineHTTPServer

    transcript = jw.job_transcript(n=120)
    params = {"max_tokens_per_chunk": 700}  # small chunks: multi-chunk map
    # uninterrupted reference over the same HTTP config surface (a plain
    # in-process server with the cli's default PipelineConfig)
    ref = EngineHTTPServer(MockEngine(seed=0), port=0,
                           batch_window_s=0.01,
                           jobs_dir=str(tmp_path / "ref"))
    ref.start_background()
    try:
        base = f"http://{ref.host}:{ref.port}"
        _status, doc = _http("POST", f"{base}/v1/jobs",
                             {"transcript": transcript, "params": params})
        jid = doc["id"]
        want = _poll_job(base, jid)
    finally:
        ref.shutdown()
    assert want["status"] == "done"
    assert want["progress"]["num_chunks"] >= 3

    jobs_dir = tmp_path / "jobs"
    jobs_dir.mkdir()
    pidfile = tmp_path / "child.pid"
    port = _free_port()
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        LMRS_SUPERVISE_PIDFILE=str(pidfile),
        LMRS_SUPERVISE_POLL_S="0.3",
        LMRS_SUPERVISE_BACKOFF_S="0.1",
        # pace the journal so the SIGKILL window mid-map is wide and
        # machine-speed independent (stalls never change what is written)
        LMRS_FAULT_PLAN=json.dumps({"faults": [
            {"site": "journal.append", "every": 1,
             "action": "stall", "stall_s": 0.3}]}))
    sup = subprocess.Popen(
        [sys.executable, "-m", "lmrs_tpu.serving.cli", "--supervise",
         "--backend", "mock", "--port", str(port),
         "--jobs-dir", str(jobs_dir), "-q"],
        env=env, cwd="/root/repo",
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    base = f"http://127.0.0.1:{port}"
    try:
        _wait_healthy(base, sup)
        pid1 = int(pidfile.read_text())
        _status, doc = _http("POST", f"{base}/v1/jobs",
                             {"transcript": transcript, "params": params})
        jid2 = doc["id"]
        wal = jobs_dir / f"{jid2}.wal"
        _wait_for_wal(wal, "chunk_done", 2)
        os.kill(pid1, signal.SIGKILL)  # kill the CHILD, not the supervisor
        # the supervisor notices and respawns: new child pid, healthz back
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                if (pidfile.exists()
                        and int(pidfile.read_text() or 0) != pid1
                        and _http("GET", f"{base}/healthz",
                                  timeout=2)[0] == 200):
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.2)
        else:
            raise TimeoutError("supervisor never respawned the child")
        state = jl.rebuild_state(jl.replay(wal)[0])
        assert state["done"] is None, "kill landed after completion"
        final = _poll_job(base, jid2)
        assert final["status"] == "done"
        assert final["recovered"] is True
        assert final["progress"]["num_resumed_chunks"] >= 2
        assert final["result"]["summary"] == want["result"]["summary"]
    finally:
        sup.terminate()
        try:
            sup.wait(timeout=30)
        except subprocess.TimeoutExpired:
            sup.kill()
            sup.wait(timeout=10)


def _wait_healthy(base: str, proc, deadline_s: float = 90.0) -> None:
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if proc.poll() is not None:
            raise RuntimeError("supervisor died: "
                               + proc.stderr.read().decode()[-2000:])
        try:
            if _http("GET", f"{base}/healthz", timeout=2)[0] == 200:
                return
        except OSError:
            pass
        time.sleep(0.1)
    raise TimeoutError(f"{base} never became healthy")


def _wait_for_wal(wal, rec_type: str, n: int,
                  deadline_s: float = 120.0) -> None:
    from lmrs_tpu.jobs import journal as jl

    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if wal.exists():
            recs, _ = jl.replay(wal)
            if sum(1 for r in recs if r.get("type") == rec_type) >= n:
                return
        time.sleep(0.05)
    raise TimeoutError(f"never saw {n} {rec_type} record(s) in {wal}")


def _poll_job(base: str, jid: str, deadline_s: float = 120.0) -> dict:
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        _status, doc = _http("GET", f"{base}/v1/jobs/{jid}")
        if doc.get("status") in ("done", "failed", "degraded",
                                 "cancelled"):
            return doc
        time.sleep(0.2)
    raise TimeoutError(f"job {jid} never finished")


def test_supervisor_wedged_healthz_is_503(monkeypatch, tmp_path):
    """The wedge signature the supervisor kills on: a server whose engine
    reports wedged answers /healthz with 503 + ``"wedged": true``."""
    from lmrs_tpu.serving.server import EngineHTTPServer

    class WedgedEngine(MockEngine):
        def wedged(self) -> bool:
            return True

    srv = EngineHTTPServer(WedgedEngine(), port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _http("GET", f"http://{srv.host}:{srv.port}/healthz")
        assert ei.value.code == 503
        doc = json.loads(ei.value.read())
        assert doc["wedged"] is True
        from lmrs_tpu.serving.supervisor import Supervisor

        sup = Supervisor(["--backend", "mock"], host=srv.host,
                         port=srv.port)
        healthy, wedged = sup._poll_health()
        assert (healthy, wedged) == (False, True)
    finally:
        srv.shutdown()
