"""Eval subsystem: ROUGE metrics + parity harness (SURVEY.md §7.2 step 7)."""

import json

import pytest

from lmrs_tpu.eval.rouge import rouge_l, rouge_n, rouge_scores, tokenize
from lmrs_tpu.eval.parity import evaluate_parity, load_baseline, run_parity


def test_tokenize_lowercases_and_strips_punctuation():
    assert tokenize("Hello, World! 42.") == ["hello", "world", "42"]


def test_rouge_identical_is_one():
    s = "the quick brown fox jumps over the lazy dog"
    for scores in (rouge_n(s, s, 1), rouge_n(s, s, 2), rouge_l(s, s)):
        assert scores["precision"] == pytest.approx(1.0)
        assert scores["recall"] == pytest.approx(1.0)
        assert scores["f"] == pytest.approx(1.0)


def test_rouge_disjoint_is_zero():
    assert rouge_l("alpha beta gamma", "delta epsilon zeta")["f"] == 0.0
    assert rouge_n("alpha beta", "gamma delta", 1)["f"] == 0.0


def test_rouge_empty_inputs():
    assert rouge_l("", "reference text")["f"] == 0.0
    assert rouge_l("candidate text", "")["f"] == 0.0
    assert rouge_n("", "", 1)["f"] == 0.0


def test_rouge_l_classic_example():
    # Lin (2004): LCS("police killed the gunman", "police kill the gunman")
    # = "police the gunman" → R = P = 3/4.
    s = rouge_l("police kill the gunman", "police killed the gunman")
    assert s["recall"] == pytest.approx(0.75)
    assert s["precision"] == pytest.approx(0.75)


def test_rouge_1_clipping():
    # candidate repeats "the" 4x; reference has it twice → clipped to 2 matches.
    s = rouge_n("the the the the", "the cat the dog", 1)
    assert s["precision"] == pytest.approx(2 / 4)
    assert s["recall"] == pytest.approx(2 / 4)


def test_rouge_l_is_subsequence_not_substring():
    # "a c e" is a subsequence of "a b c d e" (LCS=3) though not contiguous.
    s = rouge_l("a c e", "a b c d e")
    assert s["recall"] == pytest.approx(3 / 5)
    assert s["precision"] == pytest.approx(1.0)


def test_rouge_scores_multi_reference_takes_best():
    scores = rouge_scores("the cat sat", ["totally unrelated words", "the cat sat"])
    assert scores["rougeL"]["f"] == pytest.approx(1.0)
    assert scores["rouge1"]["f"] == pytest.approx(1.0)


def test_load_baseline_plain_and_json(tmp_path):
    txt = tmp_path / "base.txt"
    txt.write_text("A plain summary.")
    assert load_baseline(txt) == "A plain summary."
    js = tmp_path / "base.json"
    js.write_text(json.dumps({"summary": "From JSON.", "meta": {"model": "gpt-4o"}}))
    assert load_baseline(js) == "From JSON."


def test_load_baseline_rejects_json_without_summary(tmp_path):
    js = tmp_path / "api.json"
    js.write_text(json.dumps({"choices": [{"message": {"content": "hi"}}]}))
    with pytest.raises(ValueError, match="no top-level 'summary'"):
        load_baseline(js)
    arr = tmp_path / "arr.json"
    arr.write_text("[1, 2, 3]")
    with pytest.raises(ValueError, match="JSON array"):
        load_baseline(arr)


def test_rouge_scores_empty_references_raises():
    from lmrs_tpu.eval.rouge import rouge_scores

    with pytest.raises(ValueError, match="at least one reference"):
        rouge_scores("candidate", [])


def test_evaluate_parity_gate():
    r = evaluate_parity("the meeting covered budget and hiring",
                        "the meeting covered budget and hiring", threshold=0.9)
    assert r.passed and r.rougeL_f == pytest.approx(1.0)
    r2 = evaluate_parity("completely different text here",
                         "the meeting covered budget and hiring", threshold=0.9)
    assert not r2.passed


def test_run_parity_end_to_end_mock(transcript):
    """Self-parity: score the mock pipeline against its own prior output."""
    from lmrs_tpu.config import EngineConfig, PipelineConfig
    from lmrs_tpu.pipeline import TranscriptSummarizer

    cfg = PipelineConfig(engine=EngineConfig(backend="mock"))
    s = TranscriptSummarizer(cfg)
    try:
        baseline = s.summarize(transcript)["summary"]
    finally:
        s.shutdown()

    report = run_parity(transcript, baseline, cfg, threshold=0.9)
    assert report.passed, report.to_dict()
    assert report.chunks > 0
    assert report.wall_s > 0
    assert report.chunks_per_sec > 0
    d = report.to_dict()
    assert d["passed"] is True and "rougeL_f" in d
