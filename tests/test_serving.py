"""HTTP serving front-end: wire-format compatibility + micro-batching.

The server speaks the two formats the reference's clients produce
(llm_executor.py:278-289 OpenAI, :343-371 Anthropic), so these tests act as
the reference's counterpart: they POST reference-shaped bodies and read the
exact response fields the reference reads back.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from lmrs_tpu.engine.api import GenerationRequest, GenerationResult
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.serving.server import EngineHTTPServer


class CountingEngine:
    """Mock engine wrapper that records generate_batch call sizes."""

    def __init__(self):
        self.inner = MockEngine()
        self.batch_sizes: list[int] = []

    def generate_batch(self, requests, on_tokens=None):
        self.batch_sizes.append(len(requests))
        return self.inner.generate_batch(requests, on_tokens=on_tokens)

    def shutdown(self):
        pass

    def engine_metrics(self):
        return {"backend": "counting"}


@pytest.fixture
def server():
    engine = CountingEngine()
    srv = EngineHTTPServer(engine, port=0, batch_window_s=0.05)
    srv.start_background()
    srv.engine_wrapper = engine
    yield srv
    srv.shutdown()


def _post(server, path: str, body: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        f"http://{server.host}:{server.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(server, path: str):
    with urllib.request.urlopen(
        f"http://{server.host}:{server.port}{path}", timeout=10
    ) as resp:
        return resp.status, json.loads(resp.read())


def test_openai_chat_completions(server):
    # exactly the body shape the reference builds (llm_executor.py:278-289)
    status, out = _post(server, "/v1/chat/completions", {
        "model": "gpt-4",
        "messages": [
            {"role": "system", "content": "You are a summarizer."},
            {"role": "user", "content": "Summarize: the meeting covered hiring."},
        ],
        "max_tokens": 64,
        "temperature": 0.3,
    })
    assert status == 200
    assert out["object"] == "chat.completion"
    # the fields the reference reads back (llm_executor.py:304-317)
    text = out["choices"][0]["message"]["content"]
    assert isinstance(text, str) and text
    assert out["choices"][0]["finish_reason"] in ("stop", "length")
    usage = out["usage"]
    assert usage["total_tokens"] == usage["prompt_tokens"] + usage["completion_tokens"]


def test_anthropic_messages(server):
    status, out = _post(server, "/v1/messages", {
        "model": "claude-3-sonnet",
        "system": "You are a summarizer.",
        "messages": [{"role": "user", "content": "Summarize: budget review."}],
        "max_tokens": 64,
    })
    assert status == 200
    assert out["type"] == "message"
    # fields the reference reads back (llm_executor.py:389-400)
    assert out["content"][0]["text"]
    assert out["stop_reason"] in ("end_turn", "max_tokens")
    assert out["usage"]["input_tokens"] > 0


def test_models_healthz_metrics(server):
    assert _get(server, "/healthz")[0] == 200
    status, models = _get(server, "/v1/models")
    assert status == 200 and models["data"][0]["id"] == "lmrs-tpu"
    status, metrics = _get(server, "/metrics")
    assert status == 200 and "engine" in metrics


def test_bad_json_is_400(server):
    req = urllib.request.Request(
        f"http://{server.host}:{server.port}/v1/chat/completions",
        data=b"{not json", headers={"Content-Type": "application/json"},
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=10)
    assert e.value.code == 400


def test_unknown_route_is_404(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, "/v2/nope", {})
    assert e.value.code == 404


def test_concurrent_requests_pool_into_one_batch(server):
    """A reference-style semaphore fan-out (llm_executor.py:133-147) should
    land as few pooled generate_batch calls, not one call per request."""
    n = 8
    results: list[dict] = [None] * n  # type: ignore[list-item]

    def call(i: int):
        _, out = _post(server, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": f"chunk {i}"}],
            "max_tokens": 32,
        })
        results[i] = out

    threads = [threading.Thread(target=call, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert all(r is not None for r in results)
    # distinct prompts produce distinct mock outputs — no cross-wiring
    texts = {r["choices"][0]["message"]["content"] for r in results}
    assert len(texts) == n
    sizes = server.engine_wrapper.batch_sizes
    assert sum(sizes) == n
    assert max(sizes) > 1, f"no pooling happened: {sizes}"


def test_anthropic_system_role_in_messages(server):
    """The reference's own Anthropic client puts the system prompt inside
    messages[] with role='system' (llm_executor.py:350-358); it must land in
    the system prompt, not be relabeled as an assistant turn."""
    status, out = _post(server, "/v1/messages", {
        "messages": [
            {"role": "system", "content": "You are a summarizer."},
            {"role": "user", "content": "Summarize: planning sync."},
        ],
        "max_tokens": 64,
    })
    assert status == 200
    assert "[assistant]" not in out["content"][0]["text"]


def test_anthropic_system_content_blocks(server):
    """Top-level system given as a content-block list (valid Anthropic shape)
    must flatten, not 500 on a TypeError."""
    status, out = _post(server, "/v1/messages", {
        "system": [{"type": "text", "text": "You are a summarizer."}],
        "messages": [{"role": "user", "content": "Summarize: retro notes."}],
        "max_tokens": 64,
    })
    assert status == 200 and out["content"][0]["text"]


def _post_sse(server, path: str, body: dict, timeout: float = 30.0):
    """POST with stream:true and parse the SSE body into
    [(event_or_None, parsed_data)] frames."""
    req = urllib.request.Request(
        f"http://{server.host}:{server.port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        raw = resp.read().decode()
    frames = []
    event = None
    for line in raw.splitlines():
        if line.startswith("event: "):
            event = line[len("event: "):]
        elif line.startswith("data: "):
            data = line[len("data: "):]
            frames.append((event, data if data == "[DONE]"
                           else json.loads(data)))
            event = None
    return frames


def test_openai_streaming(server):
    """stream:true must produce parseable chat.completion.chunk SSE whose
    concatenated deltas equal the non-streamed completion (the streaming
    form of the API at llm_executor.py:292)."""
    body = {
        "messages": [{"role": "user", "content": "Summarize: hiring sync."}],
        "max_tokens": 64,
        "stream_options": {"include_usage": True},
    }
    _, plain = _post(server, "/v1/chat/completions", body)
    frames = _post_sse(server, "/v1/chat/completions",
                       {**body, "stream": True})
    assert frames[-1][1] == "[DONE]"
    chunks = [d for _, d in frames[:-1]]
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    text = "".join(c["choices"][0]["delta"].get("content", "")
                   for c in chunks)
    assert text == plain["choices"][0]["message"]["content"]
    final = chunks[-1]
    assert final["choices"][0]["finish_reason"] in ("stop", "length")
    assert final["usage"]["total_tokens"] > 0  # stream_options.include_usage


def test_anthropic_streaming(server):
    """stream:true on /v1/messages must emit the Anthropic event sequence
    (message_start .. message_stop) with text_delta frames that concatenate
    to the non-streamed text."""
    body = {
        "messages": [{"role": "user", "content": "Summarize: budget review."}],
        "max_tokens": 64,
    }
    _, plain = _post(server, "/v1/messages", body)
    frames = _post_sse(server, "/v1/messages", {**body, "stream": True})
    events = [e for e, _ in frames]
    assert events[0] == "message_start"
    assert events[1] == "content_block_start"
    assert events[-2] == "message_delta"
    assert events[-1] == "message_stop"
    deltas = [d for e, d in frames if e == "content_block_delta"]
    assert deltas, "no text deltas streamed"
    text = "".join(d["delta"]["text"] for d in deltas)
    assert text == plain["content"][0]["text"]
    mdelta = [d for e, d in frames if e == "message_delta"][0]
    assert mdelta["delta"]["stop_reason"] in ("end_turn", "max_tokens")
    assert mdelta["usage"]["output_tokens"] > 0


def test_anthropic_stop_sequence_reason(server):
    """A stop-sequence hit must report stop_reason='stop_sequence', not
    'end_turn' (the wire format the server claims to implement)."""
    status, out = _post(server, "/v1/messages", {
        "messages": [{"role": "user", "content": "explain the Summary: format"}],
        "max_tokens": 64,
        "stop_sequences": ["Summary:"],
    })
    assert status == 200
    assert out["stop_reason"] == "stop_sequence"
    assert out["stop_sequence"] == "Summary:"
    assert "Summary:" not in out["content"][0]["text"]


def test_apply_stop_sequences_earliest_in_text_wins():
    from lmrs_tpu.engine.api import apply_stop_sequences

    # earliest occurrence in TEXT wins, regardless of list order — the
    # returned text never contains any requested stop string
    text, hit = apply_stop_sequences("a STOP b END c", ("END", "STOP"))
    assert (text, hit) == ("a ", "STOP")
    assert apply_stop_sequences("no stops here", ("END",)) == ("no stops here", None)
    assert apply_stop_sequences("xEND", ()) == ("xEND", None)
    # empty stop strings must not truncate the whole completion
    assert apply_stop_sequences("keep me", ("", "END")) == ("keep me", None)


def test_anthropic_bare_string_stop_sequences(server):
    """stop_sequences given as a bare string must not explode into
    per-character stops."""
    status, out = _post(server, "/v1/messages", {
        "messages": [{"role": "user", "content": "explain the Summary: format"}],
        "max_tokens": 64,
        "stop_sequences": "Summary:",
    })
    assert status == 200
    # a per-char explosion would truncate at the first 'S'/'u'/... hit and
    # report a single-character stop_sequence
    assert out["stop_sequence"] in (None, "Summary:")


def test_batcher_drains_jobs_behind_shutdown_sentinel():
    """Jobs enqueued behind the shutdown sentinel must be completed (with an
    error), not left blocking submit() forever."""
    from lmrs_tpu.serving.server import _Batcher, _Job

    class SlowEngine:
        def generate_batch(self, requests):
            import time as _t
            _t.sleep(0.2)
            return [GenerationResult(request_id=r.request_id) for r in requests]

    b = _Batcher(SlowEngine(), window_s=0.01)
    # occupy the dispatcher with a real job, then enqueue sentinel + straggler
    first = threading.Thread(
        target=b.submit, args=(GenerationRequest(prompt="x", request_id=0),))
    first.start()
    import time as _t
    _t.sleep(0.05)  # let the dispatcher pick up the first job
    straggler = _Job(GenerationRequest(prompt="y", request_id=1))
    b.queue.put(None)          # shutdown sentinel
    b.queue.put(straggler)     # enqueued BEHIND the sentinel
    b._thread.join(timeout=5)
    assert not b._thread.is_alive()
    assert straggler.event.wait(timeout=1)
    assert straggler.result is not None and straggler.result.error
    first.join(timeout=5)


def test_stop_sequence_and_cap(server):
    status, out = _post(server, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 10_000_000,  # capped server-side
        "stop": "====",
    })
    assert status == 200
    assert "====" not in out["choices"][0]["message"]["content"]


def test_streaming_through_real_scheduler():
    """SSE through the REAL continuous-batching engine (not the mock): a
    streamed HTTP request must produce multiple deltas (one per decode
    block) that concatenate to a non-streamed greedy run's text."""
    from lmrs_tpu.config import EngineConfig, ModelConfig
    from lmrs_tpu.engine.jax_engine import JaxEngine

    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                     dtype="float32")
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=24, max_batch_slots=2, seed=0,
                                 decode_block=4), mc)
    srv = EngineHTTPServer(eng, port=0, batch_window_s=0.02)
    srv.start_background()
    try:
        body = {"messages": [{"role": "user", "content": "stream me"}],
                "max_tokens": 24, "temperature": 0.0}
        _, plain = _post(srv, "/v1/chat/completions", body, timeout=120)
        frames = _post_sse(srv, "/v1/chat/completions",
                           {**body, "stream": True}, timeout=120)
        chunks = [d for _, d in frames[:-1]]
        deltas = [c["choices"][0]["delta"].get("content", "")
                  for c in chunks]
        text = "".join(deltas)
        assert text == plain["choices"][0]["message"]["content"]
        # decode_block=4 over 24 greedy tokens: streaming must be
        # incremental through the scheduler, not one final-text delta
        assert sum(1 for d in deltas if d) > 1, deltas
    finally:
        srv.shutdown()
        eng.shutdown()


def test_streaming_engine_error_emits_sse_error_frame():
    """A failing request with stream:true must deliver an in-band SSE error
    frame and close — never hang the client or emit a bare 500 after the
    event-stream headers are out."""
    engine = MockEngine(fail_pattern="EXPLODE")
    srv = EngineHTTPServer(engine, port=0, batch_window_s=0.02)
    srv.start_background()
    try:
        frames = _post_sse(srv, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "please EXPLODE now"}],
            "stream": True,
        })
        err = [d for _, d in frames
               if isinstance(d, dict) and "error" in d]
        assert err and "injected failure" in err[0]["error"]["message"]
        assert frames[-1][1] == "[DONE]"

        frames = _post_sse(srv, "/v1/messages", {
            "messages": [{"role": "user", "content": "please EXPLODE now"}],
            "stream": True,
        })
        err = [d for e, d in frames if e == "error"]
        assert err and err[0]["error"]["type"] == "api_error"
    finally:
        srv.shutdown()


def test_serve_cli_tokenizer_flag_reaches_engine_config():
    """--tokenizer on lmrs-serve must land in EngineConfig.tokenizer (the
    converted-checkpoint journey README documents)."""
    from lmrs_tpu.serving.cli import build_parser

    args = build_parser().parse_args(
        ["--backend", "jax", "--model", "tiny", "--tokenizer", "byte"])
    assert args.tokenizer == "byte"
    from lmrs_tpu.config import EngineConfig

    cfg = EngineConfig(backend=args.backend, model=args.model,
                       tokenizer=args.tokenizer or "")
    assert cfg.tokenizer == "byte"


def test_rejection_results_echo_real_request_ids():
    """submit()/submit_stream() after shutdown and the sentinel drain must
    echo the job's real rid, never a placeholder 0 — clients correlate
    failures by id (rids are assigned at enqueue now)."""
    from lmrs_tpu.serving.server import _Batcher

    b = _Batcher(MockEngine(), window_s=0.01)
    # burn a rid with a normal request so the rejection rids are provably
    # non-zero (a 0 here could be a legitimate first id OR the old bug)
    ok = b.submit(GenerationRequest(prompt="warm"))
    assert ok.request_id == 0 and ok.error is None
    b.shutdown()
    r1 = b.submit(GenerationRequest(prompt="late"))
    job = b.submit_stream(GenerationRequest(prompt="later"))
    assert r1.error and job.result.error
    assert r1.request_id == 1
    assert job.result.request_id == 2


def test_deadline_header_reaches_engine_and_sheds():
    """A relative X-LMRS-Deadline budget is anchored server-side and rides
    the GenerationRequest into the engine; an already-expired budget comes
    back finish_reason='shed' on the wire."""
    captured: list[GenerationRequest] = []

    class Capture(MockEngine):
        def generate_batch(self, requests, on_result=None, on_tokens=None):
            captured.extend(requests)
            return super().generate_batch(requests, on_result=on_result,
                                          on_tokens=on_tokens)

    srv = EngineHTTPServer(Capture(), port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        import time as _t
        body = json.dumps({"messages": [{"role": "user", "content": "hi"}],
                           "max_tokens": 16}).encode()
        req = urllib.request.Request(
            f"http://{srv.host}:{srv.port}/v1/chat/completions", data=body,
            headers={"Content-Type": "application/json",
                     "X-LMRS-Deadline": "30"}, method="POST")
        t0 = _t.time()
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["choices"][0]["finish_reason"] == "stop"
        assert captured and captured[-1].deadline_s is not None
        assert 20.0 < captured[-1].deadline_s - t0 <= 31.0

        # expired budget (body field form): shed, explicit and fast
        req2 = urllib.request.Request(
            f"http://{srv.host}:{srv.port}/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": "hi"}],
                "deadline_s": -1.0}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req2, timeout=30) as resp:
            out = json.loads(resp.read())
        assert out["choices"][0]["finish_reason"] == "shed"
        assert out["choices"][0]["message"]["content"] == ""
    finally:
        srv.shutdown()


@pytest.mark.parametrize("bad", ["soonish", "nan", "inf", "-inf"])
def test_invalid_deadline_is_400(server, bad):
    """A garbage or non-finite deadline must be rejected, not silently
    (mis)applied — a NaN budget sheds on one engine and runs unbounded on
    another, the opposite of an explicit contract either way."""
    req = urllib.request.Request(
        f"http://{server.host}:{server.port}/v1/chat/completions",
        data=json.dumps({"messages": [{"role": "user", "content": "x"}],
                         "deadline_s": bad}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=10)
    assert e.value.code == 400


def test_anthropic_wire_reports_shed(server):
    """/v1/messages must surface the deadline outcomes as stop_reason
    extension values — collapsing a zero-work shed into 'max_tokens'
    would be indistinguishable from a normal truncated completion."""
    status, out = _post(server, "/v1/messages", {
        "messages": [{"role": "user", "content": "late"}],
        "deadline_s": -1.0, "max_tokens": 16})
    assert status == 200
    assert out["stop_reason"] == "shed"
    assert out["content"][0]["text"] == ""


def test_injected_client_disconnect_cancels_nonstream_request():
    """The server.client_disconnect injection site drives the
    disconnect->cancel propagation path without a socket teardown: the
    poll reports the client gone, the batcher cancels through the engine
    hook, and the request resolves as cancelled."""
    from lmrs_tpu.testing import faults
    from lmrs_tpu.testing.faults import FaultPlan

    engine = MockEngine(latency_s=1.2)  # long enough for one 0.5s poll
    srv = EngineHTTPServer(engine, port=0, batch_window_s=0.01)
    srv.start_background()
    try:
        with faults.injected(FaultPlan(faults=[
                {"site": "server.client_disconnect", "at": [1]}])):
            status, out = _post(srv, "/v1/chat/completions", {
                "messages": [{"role": "user", "content": "vanishing"}],
                "max_tokens": 16})
        assert status == 200  # the "gone" client still gets the response
        assert out["choices"][0]["finish_reason"] == "cancelled"
    finally:
        srv.shutdown()
