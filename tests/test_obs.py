"""Telemetry subsystem tests: histogram bucket math + percentile parity,
trace-event schema validation, Prometheus exposition golden output, label
propagation, and the scheduler's per-request span chain (including the
preempt and cancel paths)."""

import json
import logging

import numpy as np
import pytest

from lmrs_tpu.obs import (
    TID_SCHED,
    Histogram,
    MetricsRegistry,
    Tracer,
    add_label_to_exposition,
    disable_tracing,
    enable_tracing,
    log_buckets,
    merge_expositions,
    req_tid,
    validate_trace_events,
    validate_trace_file,
)


@pytest.fixture
def tracer():
    """Process tracer, cleared and torn down so span state never leaks
    between tests (tracing is process-global by design)."""
    tr = enable_tracing()
    tr.clear()
    yield tr
    disable_tracing()


# ------------------------------------------------------------------ metrics


def test_histogram_bucket_math():
    h = Histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 3.0, 100.0):
        h.observe(v)
    # le semantics: 1.0 lands in the le=1 bucket, 100 overflows to +Inf
    assert h.counts == [2, 1, 1, 1]
    assert h.cumulative_counts() == [2, 3, 4, 5]
    assert h.count == 5
    assert h.sum == pytest.approx(106.0)
    h.reset()
    assert h.count == 0 and h.counts == [0, 0, 0, 0] and not h.samples


def test_histogram_percentile_parity_with_old_latency_pct():
    """percentile_report must reproduce the scheduler's former _latency_pct
    exactly: np.percentile p50/p90/p99 over the samples, seconds -> ms,
    0.1 ms precision, None when empty."""
    h = Histogram("h", buckets=(0.1, 1.0))
    assert h.percentile_report() is None
    rng = np.random.default_rng(7)
    samples = rng.gamma(2.0, 0.05, size=500).tolist()
    for v in samples:
        h.observe(v)
    p50, p90, p99 = np.percentile(np.asarray(samples), [50, 90, 99])
    expected = {"p50": round(float(p50) * 1e3, 1),
                "p90": round(float(p90) * 1e3, 1),
                "p99": round(float(p99) * 1e3, 1),
                "n": len(samples)}
    assert h.percentile_report() == expected


def test_histogram_sample_cap_drops_oldest_half():
    import lmrs_tpu.obs.metrics as om

    h = Histogram("h", buckets=(1.0,))
    old_cap = om._SAMPLE_CAP
    om._SAMPLE_CAP = 100
    try:
        for i in range(101):
            h.observe(float(i))
    finally:
        om._SAMPLE_CAP = old_cap
    # oldest half dropped, newest retained; bucket counts keep everything
    assert len(h.samples) == 51
    assert h.samples[0] == 50.0
    assert h.count == 101


def test_log_buckets_monotonic():
    b = log_buckets(0.001, 10.0)
    assert list(b) == sorted(set(b))
    assert b[0] == pytest.approx(0.001) and b[-1] == pytest.approx(10.0)
    with pytest.raises(ValueError):
        log_buckets(0.0, 1.0)


def test_registry_get_or_create_and_type_conflict():
    reg = MetricsRegistry()
    c1 = reg.counter("lmrs_a_total")
    assert reg.counter("lmrs_a_total") is c1
    with pytest.raises(ValueError):
        reg.gauge("lmrs_a_total")
    with pytest.raises(ValueError):
        reg.counter("lmrs_a_total").inc(-1)


def test_prometheus_exposition_golden():
    reg = MetricsRegistry()
    reg.counter("lmrs_reqs_total", "requests served").inc(3)
    reg.gauge("lmrs_slots", "active slots").set(2)
    h = reg.histogram("lmrs_ttft_seconds", buckets=(0.1, 1.0),
                      help="time to first token")
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    assert reg.render_prometheus() == (
        "# HELP lmrs_reqs_total requests served\n"
        "# TYPE lmrs_reqs_total counter\n"
        "lmrs_reqs_total 3\n"
        "# HELP lmrs_slots active slots\n"
        "# TYPE lmrs_slots gauge\n"
        "lmrs_slots 2\n"
        "# HELP lmrs_ttft_seconds time to first token\n"
        "# TYPE lmrs_ttft_seconds histogram\n"
        'lmrs_ttft_seconds_bucket{le="0.1"} 1\n'
        'lmrs_ttft_seconds_bucket{le="1"} 2\n'
        'lmrs_ttft_seconds_bucket{le="+Inf"} 3\n'
        "lmrs_ttft_seconds_sum 5.55\n"
        "lmrs_ttft_seconds_count 3\n"
    )


def _assert_valid_exposition(text: str) -> None:
    """Minimal format validator: single TYPE per metric, contiguous metric
    groups, cumulative bucket counts ending at _count."""
    typed: set[str] = set()
    for line in text.splitlines():
        s = line.strip()
        if not s:
            continue
        if s.startswith("# TYPE"):
            name = s.split()[2]
            assert name not in typed, f"duplicate TYPE for {name}"
            typed.add(name)
        elif not s.startswith("#"):
            assert " " in s, s


def test_label_propagation_and_merge():
    reg = MetricsRegistry()
    reg.counter("lmrs_reqs_total", "requests").inc(1)
    h = reg.histogram("lmrs_ttft_seconds", buckets=(1.0,), help="ttft")
    h.observe(0.5)
    pages = [add_label_to_exposition(reg.render_prometheus(), "host", hn)
             for hn in ("a:8000", "b:8000")]
    assert 'lmrs_reqs_total{host="a:8000"} 1' in pages[0]
    assert 'lmrs_ttft_seconds_bucket{host="b:8000",le="1"} 1' in pages[1]
    merged = merge_expositions(pages)
    _assert_valid_exposition(merged)
    # both hosts' series survive under one header, grouped contiguously
    assert merged.count("# TYPE lmrs_ttft_seconds histogram") == 1
    assert 'lmrs_ttft_seconds_count{host="a:8000"}' in merged
    assert 'lmrs_ttft_seconds_count{host="b:8000"}' in merged
    lines = merged.splitlines()
    fam = [i for i, ln in enumerate(lines) if ln.startswith("lmrs_ttft_")]
    assert fam == list(range(fam[0], fam[0] + len(fam))), "group split"


# -------------------------------------------------------------------- trace


def test_tracer_ring_bound():
    tr = Tracer(capacity=8)
    for i in range(20):
        tr.instant(f"e{i}", ts=float(i))
    assert len(tr.events()) == 8
    assert tr.recorded == 20
    assert tr.events()[0]["name"] == "e12"  # oldest dropped first


def test_trace_export_schema(tmp_path, tracer):
    tracer.instant("enqueue", tid=req_tid(0))
    tracer.complete("prefill", 1.0, 2.0, tid=req_tid(0), args={"tokens": 4})
    path = tmp_path / "t.json"
    n = tracer.export(path)
    events = validate_trace_file(path)
    assert n == len(events)
    data = json.loads(path.read_text())
    assert "traceEvents" in data  # Perfetto's expected container
    names = {e["name"] for e in events}
    # metadata survives export regardless of ring state
    assert {"process_name", "thread_name", "enqueue", "prefill"} <= names


def test_trace_validation_rejects_bad_events():
    with pytest.raises(ValueError):
        validate_trace_events([])
    with pytest.raises(ValueError):
        validate_trace_events([{"ph": "i", "ts": 0, "pid": 1, "tid": 1}])
    with pytest.raises(ValueError):
        validate_trace_events([{"name": "x", "ph": "??", "ts": 0,
                                "pid": 1, "tid": 1}])
    with pytest.raises(ValueError):  # X span without dur
        validate_trace_events([{"name": "x", "ph": "X", "ts": 0,
                                "pid": 1, "tid": 1}])


def _instant(name, args, ts=1.0):
    return {"name": name, "ph": "i", "s": "t", "ts": ts, "pid": 1,
            "tid": 10, "args": args}


def test_trace_validation_handoff_job_instant_contracts():
    """The handoff/job lifecycle instants carry contract args their
    consumers (stitcher skew anchors, jobs dashboard) parse — a dropped
    key must fail the gate, not silently break a reader."""
    # conforming instants pass
    validate_trace_events([
        _instant("handoff_export", {"pages": 4, "kv_len": 128}),
        _instant("handoff_import", {"pages": 4, "kv_len": 128, "slot": 0}),
        _instant("handoff_release", {"pages": 4, "orphaned": False}),
        _instant("job_submit", {"job": "job-abc"}),
        _instant("job_recover", {"job": "job-abc"}),
        _instant("job_resume", {"job": "job-abc", "resumed_chunks": 3}),
        _instant("job_done", {"job": "job-abc", "status": "done"}),
    ])
    # each required key missing is a schema violation
    for bad in (
        _instant("handoff_export", {"pages": 4}),            # no kv_len
        _instant("handoff_import", {"kv_len": 128}),         # no pages
        _instant("handoff_release", {"pages": 4}),           # no orphaned
        _instant("job_done", {"job": "job-abc"}),            # no status
        _instant("job_resume", {"job": "job-abc"}),          # no count
        _instant("job_submit", {}),                          # no job
    ):
        with pytest.raises(ValueError):
            validate_trace_events([bad])


def test_trace_validation_perf_attribution_args_numeric():
    """Perf-attribution args (flops_g/hbm_gb/mfu/...) must be finite
    non-negative numbers wherever they appear — a NaN or negative value
    poisons every aggregation built on the trace."""
    ok = {"name": "prefill_dispatch", "ph": "i", "s": "t", "ts": 1.0,
          "pid": 1, "tid": 0, "args": {"tokens": 512, "flops_g": 1.25}}
    validate_trace_events([ok])
    for key, val in (("flops_g", -1.0), ("flops_g", float("nan")),
                     ("hbm_gb", float("inf")), ("tokens", -5),
                     ("mfu", True), ("hbm_util", "0.5")):
        bad = {**ok, "args": {**ok["args"], key: val}}
        with pytest.raises(ValueError):
            validate_trace_events([bad])


def test_track_for_int_compat_and_trace_allocation(tracer):
    """int keys keep the legacy REQ_TID_BASE mapping; string (trace-id)
    keys allocate stable tids from a disjoint base and name their track
    trace:<id> — the metadata the cross-host stitcher keys on."""
    from lmrs_tpu.obs import TRACE_TRACK_PREFIX
    from lmrs_tpu.obs.trace import TRACE_TID_BASE

    assert tracer.track_for(7) == req_tid(7)
    t1 = tracer.track_for("trace-a")
    assert t1 == tracer.track_for("trace-a")  # stable
    t2 = tracer.track_for("trace-b")
    assert t1 != t2 and t1 >= TRACE_TID_BASE
    names = {(e["pid"], e["tid"]): e["args"]["name"]
             for e in tracer.payload()["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert names[(1, t1)] == f"{TRACE_TRACK_PREFIX}trace-a"
    assert names[(1, t2)] == f"{TRACE_TRACK_PREFIX}trace-b"


def test_stitch_traces_aligns_skewed_clocks(tracer):
    """Two synthetic host pages whose clocks disagree by 10 s (the decode
    host's import timestamps PRECEDE the export on the merged clock):
    the stitcher's handoff-pair skew anchor shifts the decode host
    forward so the stitched chain reads causally, and same-clock hosts
    are left untouched."""
    from lmrs_tpu.obs import stitch_traces, stitched_chains

    def host_page(events):
        tr = Tracer()
        tid = tr.track_for("tr-1")
        for name, ts, args in events:
            tr.instant(name, ts=ts, tid=tid, args=args)
        return tr.payload()

    t0 = 1000.0
    skew = -10.0  # decode host clock 10 s behind
    prefill = host_page([
        ("enqueue", t0, {"prompt_tokens": 8}),
        ("handoff_export", t0 + 1.0, {"pages": 2, "kv_len": 8}),
        ("handoff_release", t0 + 3.0, {"pages": 2, "orphaned": False}),
    ])
    decode = host_page([
        ("handoff_import", t0 + 2.0 + skew, {"pages": 2, "kv_len": 8}),
        ("finish", t0 + 4.0 + skew, {"reason": "stop",
                                     "completion_tokens": 4}),
    ])
    doc = stitch_traces([("pre:8000", prefill), ("dec:8000", decode)])
    validate_trace_events(doc["traceEvents"])
    off = doc["stitch"]["offsets_ms"]
    assert off["pre:8000"] == 0.0
    assert off["dec:8000"] > 0  # shifted forward to restore causality
    chains = stitched_chains(doc["traceEvents"])
    assert list(chains) == ["tr-1"]
    names = [e["name"] for e in chains["tr-1"]]
    assert names.index("handoff_export") < names.index("handoff_import")
    assert names[0] == "enqueue" and names[-1] == "finish"
    # hosts already on one clock are left untouched (0 in the interval)
    doc2 = stitch_traces([
        ("pre:8000", prefill),
        ("dec:8000", host_page([
            ("handoff_import", t0 + 2.0, {"pages": 2, "kv_len": 8}),
            ("finish", t0 + 4.0, {"reason": "stop"})]))])
    assert doc2["stitch"]["offsets_ms"]["dec:8000"] == 0.0


def test_timestamps_filter(tracer):
    tracer.complete("decode_block", 1.0, 1.5, tid=TID_SCHED)
    tracer.instant("decode_block", ts=1.0, tid=req_tid(3))
    tracer.complete("decode_block", 2.0, 2.5, tid=TID_SCHED)
    assert tracer.timestamps("decode_block", tid=TID_SCHED) == [1.0, 2.0]
    assert len(tracer.timestamps("decode_block")) == 3


# ------------------------------------------------- scheduler span chains


def _tiny_model():
    from lmrs_tpu.config import ModelConfig

    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=96,
                       dtype="float32")


def _chain(events: list[dict]) -> list[str]:
    return [e["name"] for e in events]


def test_scheduler_emits_complete_span_chain(tracer):
    """Every admitted request must emit the full lifecycle chain, in
    timestamp order, ending in finish."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=8, max_batch_slots=2, seed=0),
                    _tiny_model())
    n = 4
    reqs = [GenerationRequest(prompt=f"chain probe {i} " * (i + 1),
                              request_id=i, temperature=0.5,
                              max_new_tokens=6) for i in range(n)]
    out = eng.generate_batch(reqs)
    assert all(r.error is None for r in out)
    spans = tracer.spans_by_tid()
    for rid in range(n):
        evs = spans.get(req_tid(rid), [])
        names = _chain(evs)
        for required in ("enqueue", "admit", "prefill", "first_token",
                         "finish"):
            assert required in names, f"rid {rid}: {names}"
        # chain ordering: lifecycle milestones are monotonically timestamped
        order = [names.index(x) for x in
                 ("enqueue", "admit", "first_token", "finish")]
        assert order == sorted(order), names
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts)
    # the scheduler track carries batch-level dispatch spans
    sched_names = _chain(spans.get(TID_SCHED, []))
    assert "decode_block" in sched_names and "prefill_dispatch" in sched_names
    eng.shutdown()


def test_scheduler_span_chain_preempt_path(tracer):
    """A preempted request's track must show preempt and a SECOND admit
    (the continuation), still ending in finish."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=40, max_batch_slots=4, seed=0,
                                 page_size=16, num_pages=10, decode_block=4),
                    _tiny_model())
    reqs = [GenerationRequest(prompt=f"pressure probe {i} " * 3,
                              request_id=i, temperature=0.0,
                              max_new_tokens=40) for i in range(4)]
    out = eng.generate_batch(reqs)
    assert all(r.error is None for r in out)
    assert eng._scheduler.metrics["preemptions"] > 0
    spans = tracer.spans_by_tid()
    preempted = [rid for rid in range(4)
                 if "preempt" in _chain(spans[req_tid(rid)])]
    assert preempted, "no request track recorded the preemption"
    for rid in preempted:
        names = _chain(spans[req_tid(rid)])
        assert names.count("admit") >= 2, names  # continuation re-admitted
        assert names[-1] == "finish", names
    # non-preempted requests still finish their plain chains
    for rid in range(4):
        assert "finish" in _chain(spans[req_tid(rid)])
    eng.shutdown()


def test_scheduler_span_chain_cancel_paths(tracer):
    """Both cancel paths emit a terminal cancel event: a live slot swept at
    a block boundary, and a queued request that never prefills."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=32, max_batch_slots=1, seed=0,
                                 decode_block=4), _tiny_model())
    reqs = [GenerationRequest(prompt="short", request_id=0, temperature=0.5,
                              max_new_tokens=2),
            GenerationRequest(prompt="long cancelled " * 4, request_id=1,
                              temperature=0.5, max_new_tokens=32),
            GenerationRequest(prompt="queued cancelled", request_id=2,
                              temperature=0.5, max_new_tokens=32)]

    def on_result(res, submit):
        if res.request_id == 0:  # rid 1 is decoding, rid 2 still queued
            eng.cancel(1)
            eng.cancel(2)

    out = eng.generate_batch(reqs, on_result=on_result)
    by_id = {r.request_id: r for r in out}
    assert by_id[1].finish_reason == "cancelled"
    assert by_id[2].finish_reason == "cancelled"
    spans = tracer.spans_by_tid()
    # live-slot path: full chain up to cancel
    names1 = _chain(spans[req_tid(1)])
    assert "admit" in names1 and names1[-1] == "cancel", names1
    # queued path: enqueued but never admitted
    names2 = _chain(spans[req_tid(2)])
    assert names2[0] == "enqueue" and names2[-1] == "cancel", names2
    assert "admit" not in names2, names2
    eng.shutdown()


def test_metrics_report_superset_of_pre_pr_keys():
    """metrics_report() keys must remain a superset of the pre-registry
    report (bench windowing and the CLI banner read these)."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=6, max_batch_slots=2, seed=0),
                    _tiny_model())
    eng.generate_batch([GenerationRequest(prompt="superset probe",
                                          request_id=0, max_new_tokens=4)])
    report = eng.engine_metrics()
    pre_pr = {"prefill_tokens", "decode_tokens", "prefill_tokens_per_sec",
              "decode_tokens_per_sec", "mean_decode_occupancy",
              "peak_kv_page_utilization", "scheduler_seconds",
              "blocked_seconds", "host_seconds", "preemptions", "stalls",
              "cancelled", "peak_active_slots", "ttft_ms",
              "decode_block_gap_ms", "prefix_cache"}
    assert pre_pr <= set(report), pre_pr - set(report)
    # raw snapshot keeps the old dict's keys for windowed deltas
    raw = eng._scheduler.metrics
    pre_pr_raw = {"prefill_tokens", "decode_tokens", "decode_dispatches",
                  "occupancy_sum", "peak_pages_in_use", "run_seconds",
                  "spec_accepted_tokens", "preemptions", "stalls",
                  "peak_active_slots", "cancelled", "blocked_seconds",
                  "prefix_queries", "prefix_hits", "prefix_tokens_reused"}
    assert pre_pr_raw <= set(raw)
    # Prometheus view exposes the ttft histogram the ISSUE names
    text = eng._scheduler.registry.render_prometheus()
    assert "lmrs_ttft_seconds_bucket" in text
    _assert_valid_exposition(text)
    eng.shutdown()


def test_perf_attribution_surface():
    """The live-attribution block rides metrics_report() and the
    Prometheus page (histograms + _last gauges + model-work counters);
    after real dispatches the model-work counters are nonzero and the
    step-gap histogram sampled (CPU run: ratios may be empty — compiling
    shapes and the garbage guard legitimately skip samples)."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=16, max_batch_slots=2, seed=0,
                                 decode_block=4), _tiny_model())
    for rid in range(2):  # second wave runs warm shapes
        eng.generate_batch([GenerationRequest(
            prompt="attribution probe " * 3, request_id=rid,
            temperature=0.0, max_new_tokens=12)])
    pa = eng.engine_metrics()["perf_attribution"]
    assert {"prefill_mfu", "decode_hbm_util", "step_gap_ms",
            "model_prefill_gflops", "model_decode_gb",
            "rtt_ms"} <= set(pa)
    assert pa["model_prefill_gflops"] > 0
    assert pa["model_decode_gb"] > 0
    assert (pa["step_gap_ms"] or {}).get("n", 0) >= 1
    text = eng._scheduler.registry.render_prometheus()
    for name in ("lmrs_prefill_mfu_ratio_bucket",
                 "lmrs_decode_hbm_util_ratio_bucket",
                 "lmrs_step_gap_ms_bucket",
                 "lmrs_prefill_model_flops_total",
                 "lmrs_decode_model_bytes_total",
                 "lmrs_step_gap_ms_last"):
        assert name in text, name
    _assert_valid_exposition(text)
    # warmup isolation: the distributions reset, the counters persist
    eng._scheduler.reset_latency_stats()
    pa2 = eng.engine_metrics()["perf_attribution"]
    assert pa2["step_gap_ms"] is None
    assert pa2["model_prefill_gflops"] == pa["model_prefill_gflops"]
    eng.shutdown()


# ----------------------------------------------------------------- logging


def test_setup_logging_honors_repeated_calls(capsys):
    import io

    from lmrs_tpu.utils.logging import setup_logging

    root = logging.getLogger("lmrs")
    saved = root.handlers[:]
    root.handlers = []
    try:
        setup_logging(quiet=False)
        assert root.level == logging.INFO
        buf = io.StringIO()
        setup_logging(quiet=True, stream=buf)  # later call must win
        assert root.level == logging.WARNING
        logging.getLogger("lmrs.test").warning("to the new stream")
        assert "to the new stream" in buf.getvalue()
    finally:
        root.handlers = saved


def test_setup_logging_json_formatter(monkeypatch):
    import io

    from lmrs_tpu.utils.logging import setup_logging

    root = logging.getLogger("lmrs")
    saved = root.handlers[:]
    root.handlers = []
    try:
        monkeypatch.setenv("LMRS_LOG_JSON", "1")
        buf = io.StringIO()
        setup_logging(stream=buf)
        logging.getLogger("lmrs.test").info("structured hello")
        line = buf.getvalue().strip()
        entry = json.loads(line)
        assert entry["msg"] == "structured hello"
        assert entry["level"] == "INFO"
        assert entry["logger"] == "lmrs.test"
    finally:
        root.handlers = saved


# ------------------------------------------------------------------ serving


def test_metrics_content_negotiation_and_router_labels():
    """GET /metrics serves JSON by default and Prometheus text under
    Accept: text/plain; the router's fleet page carries host labels and
    marks unreachable backends."""
    import urllib.request

    from lmrs_tpu.engine.mock import MockEngine
    from lmrs_tpu.serving.router import RouterEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    servers = [EngineHTTPServer(MockEngine(), port=0) for _ in range(2)]
    for s in servers:
        s.start_background()
    urls = [f"{s.host}:{s.port}" for s in servers]
    try:
        base = f"http://{urls[0]}/metrics"
        body = urllib.request.urlopen(urllib.request.Request(base)).read()
        assert "engine" in json.loads(body)
        req = urllib.request.Request(base, headers={"Accept": "text/plain"})
        resp = urllib.request.urlopen(req)
        assert "text/plain" in resp.headers["Content-Type"]
        text = resp.read().decode()
        assert "lmrs_http_requests_total" in text
        _assert_valid_exposition(text)

        # router aggregation: live hosts labeled, dead host visible
        router = RouterEngine(urls + ["127.0.0.1:1"])
        page = router.prometheus_metrics()
        _assert_valid_exposition(page)
        for u in urls:
            assert f'lmrs_http_requests_total{{host="{u}"}}' in page
            assert f'lmrs_router_host_scrape_ok{{host="{u}"}} 1' in page
        # dead host: router still BELIEVES it healthy (no request traffic
        # has condemned it), but the scrape failure is alertable
        assert 'lmrs_router_host_up{host="127.0.0.1:1"} 1' in page
        assert 'lmrs_router_host_scrape_ok{host="127.0.0.1:1"} 0' in page
        agg = router.engine_metrics()
        dead = [row for row in agg["per_host"]
                if row["host"] == "127.0.0.1:1"][0]
        assert dead.get("metrics_unreachable") is True
        assert "metrics" not in dead
        live = [row for row in agg["per_host"] if row["host"] == urls[0]][0]
        assert "metrics_unreachable" not in live

        # a server FRONTING the router must merge the fleet page with its
        # own counters into one valid exposition (the backends emit the
        # same lmrs_http_* families — exactly one TYPE header may survive)
        front = EngineHTTPServer(router, port=0)
        front.start_background()
        try:
            freq = urllib.request.Request(
                f"http://{front.host}:{front.port}/metrics",
                headers={"Accept": "text/plain"})
            ftext = urllib.request.urlopen(freq).read().decode()
            _assert_valid_exposition(ftext)
            assert ftext.count("# TYPE lmrs_http_requests_total counter") == 1
            assert f'lmrs_http_requests_total{{host="{urls[0]}"}}' in ftext
            assert "\nlmrs_http_requests_total 0\n" in ftext  # its own, bare
        finally:
            front.shutdown()
        router.shutdown()
    finally:
        for s in servers:
            s.shutdown()
