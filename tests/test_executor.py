"""Tests for the map executor's scheduling contract (retry, degrade, order,
accounting) against the mock engine."""

import pytest

from lmrs_tpu.config import EngineConfig
from lmrs_tpu.data.chunker import TranscriptChunker
from lmrs_tpu.data.preprocessor import preprocess_transcript
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.prompts import DEFAULT_MAP_PROMPT


def _chunks(segments, n_budget=150):
    processed = preprocess_transcript(segments)
    return TranscriptChunker(
        max_tokens_per_chunk=n_budget, overlap_tokens=0, context_tokens=30
    ).chunk_transcript(processed)


def _executor(**cfg_kw):
    cfg = EngineConfig(backend="mock", retry_delay=0.0, **cfg_kw)
    return MapExecutor(MockEngine(), cfg)


def test_process_chunks_fills_summaries(segments):
    chunks = _chunks(segments)
    ex = _executor()
    out = ex.process_chunks(chunks, DEFAULT_MAP_PROMPT)
    assert len(out) == len(chunks)
    for c in out:
        assert c.summary and c.error is None
        assert c.tokens_used > 0


def test_order_restoration(segments):
    chunks = _chunks(segments)
    shuffled = list(reversed(chunks))
    out = _executor().process_chunks(shuffled, DEFAULT_MAP_PROMPT)
    assert [c.chunk_index for c in out] == sorted(c.chunk_index for c in chunks)


def test_accounting_counters(segments):
    chunks = _chunks(segments)
    ex = _executor()
    ex.process_chunks(chunks, DEFAULT_MAP_PROMPT)
    st = ex.stats()
    assert st["total_requests"] == len(chunks)
    assert st["failed_requests"] == 0
    assert st["total_tokens_used"] > 0


def test_degrade_to_error_summary(segments):
    """Exhausted chunks degrade to inline error summaries; pipeline continues
    (llm_executor.py:219-225 contract)."""
    chunks = _chunks(segments)
    victim = chunks[1].text_with_context[:50]
    cfg = EngineConfig(backend="mock", retry_delay=0.0, retry_attempts=2)
    ex = MapExecutor(MockEngine(fail_pattern=victim), cfg)
    out = ex.process_chunks(chunks, DEFAULT_MAP_PROMPT)
    bad = [c for c in out if c.error]
    good = [c for c in out if not c.error]
    assert len(bad) >= 1
    assert all(c.summary.startswith("[Error processing chunk:") for c in bad)
    assert all(c.summary for c in good)
    assert ex.failed_requests == len(bad)


def test_retry_then_succeed(segments):
    """A transiently failing engine succeeds on retry."""

    class FlakyEngine(MockEngine):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def generate_batch(self, requests):
            self.calls += 1
            if self.calls == 1:
                from lmrs_tpu.engine.api import GenerationResult

                return [
                    GenerationResult(request_id=r.request_id, finish_reason="error",
                                     error="transient")
                    for r in requests
                ]
            return super().generate_batch(requests)

    cfg = EngineConfig(backend="mock", retry_delay=0.0, retry_attempts=3,
                       max_concurrent_requests=100)
    ex = MapExecutor(FlakyEngine(), cfg)
    results = ex.run_requests([GenerationRequest(prompt="Hello. World.", request_id=7)])
    assert results[0].error is None
    assert results[0].request_id == 7


def test_mock_engine_deterministic():
    eng = MockEngine(seed=3)
    req = GenerationRequest(prompt="One fact here. Another fact there. [00:10] noted.")
    a = eng.generate_batch([req])[0]
    b = eng.generate_batch([req])[0]
    assert a.text == b.text
    assert "[00:10]" in a.text


def test_internally_scheduled_engine_gets_whole_queue():
    """Engines with their own admission control receive all requests in one
    generate_batch call (no wave barrier)."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest, GenerationResult
    from lmrs_tpu.engine.executor import MapExecutor

    calls = []

    class FakeEngine:
        schedules_internally = True

        def generate_batch(self, requests):
            calls.append(len(requests))
            return [GenerationResult(request_id=r.request_id, text="ok")
                    for r in requests]

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    ex = MapExecutor(FakeEngine(), EngineConfig(max_concurrent_requests=2))
    reqs = [GenerationRequest(prompt=f"r{i}", request_id=i) for i in range(7)]
    out = ex.run_requests(reqs)
    assert [r.request_id for r in out] == list(range(7))
    assert calls == [7]  # one call with the whole queue, not ceil(7/2) waves


def test_chunk_groups_interleave_round_robin():
    """Multi-transcript pooling must admit round-robin across groups
    (VERDICT r2 item 9): FIFO admission of whole groups would starve later
    transcripts — completion skew should track transcript size, not
    submission order."""
    from lmrs_tpu.data.chunker import Chunk
    from lmrs_tpu.engine.executor import MapExecutor
    from lmrs_tpu.engine.mock import MockEngine

    class RecordingEngine(MockEngine):
        def __init__(self):
            super().__init__()
            self.seen: list[str] = []

        def generate_batch(self, requests, on_result=None, on_tokens=None):
            self.seen.extend(r.prompt.split("|")[0] for r in requests)
            return super().generate_batch(requests, on_result, on_tokens)

    eng = RecordingEngine()
    ex = MapExecutor(eng)
    groups = [
        [Chunk(text=f"A{i}", text_with_context=f"A{i}|body") for i in range(4)],
        [Chunk(text=f"B{i}", text_with_context=f"B{i}|body") for i in range(2)],
        [Chunk(text=f"C{i}", text_with_context=f"C{i}|body") for i in range(3)],
    ]
    ex.process_chunk_groups(groups, "{transcript}")
    # round-robin until groups drain: A0 B0 C0 A1 B1 C1 A2 C2 A3
    assert eng.seen == ["A0", "B0", "C0", "A1", "B1", "C1", "A2", "C2", "A3"]
    # every chunk still got its own summary (flat/results stayed aligned)
    for g in groups:
        assert all(c.summary is not None for c in g)
