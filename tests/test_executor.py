"""Tests for the map executor's scheduling contract (retry, degrade, order,
accounting) against the mock engine."""

import pytest

from lmrs_tpu.config import EngineConfig
from lmrs_tpu.data.chunker import TranscriptChunker
from lmrs_tpu.data.preprocessor import preprocess_transcript
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.prompts import DEFAULT_MAP_PROMPT


def _chunks(segments, n_budget=150):
    processed = preprocess_transcript(segments)
    return TranscriptChunker(
        max_tokens_per_chunk=n_budget, overlap_tokens=0, context_tokens=30
    ).chunk_transcript(processed)


def _executor(**cfg_kw):
    cfg = EngineConfig(backend="mock", retry_delay=0.0, **cfg_kw)
    return MapExecutor(MockEngine(), cfg)


def test_process_chunks_fills_summaries(segments):
    chunks = _chunks(segments)
    ex = _executor()
    out = ex.process_chunks(chunks, DEFAULT_MAP_PROMPT)
    assert len(out) == len(chunks)
    for c in out:
        assert c.summary and c.error is None
        assert c.tokens_used > 0


def test_order_restoration(segments):
    chunks = _chunks(segments)
    shuffled = list(reversed(chunks))
    out = _executor().process_chunks(shuffled, DEFAULT_MAP_PROMPT)
    assert [c.chunk_index for c in out] == sorted(c.chunk_index for c in chunks)


def test_accounting_counters(segments):
    chunks = _chunks(segments)
    ex = _executor()
    ex.process_chunks(chunks, DEFAULT_MAP_PROMPT)
    st = ex.stats()
    assert st["total_requests"] == len(chunks)
    assert st["failed_requests"] == 0
    assert st["total_tokens_used"] > 0


def test_degrade_to_error_summary(segments):
    """Exhausted chunks degrade to inline error summaries; pipeline continues
    (llm_executor.py:219-225 contract)."""
    chunks = _chunks(segments)
    victim = chunks[1].text_with_context[:50]
    cfg = EngineConfig(backend="mock", retry_delay=0.0, retry_attempts=2)
    ex = MapExecutor(MockEngine(fail_pattern=victim), cfg)
    out = ex.process_chunks(chunks, DEFAULT_MAP_PROMPT)
    bad = [c for c in out if c.error]
    good = [c for c in out if not c.error]
    assert len(bad) >= 1
    assert all(c.summary.startswith("[Error processing chunk:") for c in bad)
    assert all(c.summary for c in good)
    assert ex.failed_requests == len(bad)


def test_retry_then_succeed(segments):
    """A transiently failing engine succeeds on retry."""

    class FlakyEngine(MockEngine):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def generate_batch(self, requests):
            self.calls += 1
            if self.calls == 1:
                from lmrs_tpu.engine.api import GenerationResult

                return [
                    GenerationResult(request_id=r.request_id, finish_reason="error",
                                     error="transient")
                    for r in requests
                ]
            return super().generate_batch(requests)

    cfg = EngineConfig(backend="mock", retry_delay=0.0, retry_attempts=3,
                       max_concurrent_requests=100)
    ex = MapExecutor(FlakyEngine(), cfg)
    results = ex.run_requests([GenerationRequest(prompt="Hello. World.", request_id=7)])
    assert results[0].error is None
    assert results[0].request_id == 7


def test_mock_engine_deterministic():
    eng = MockEngine(seed=3)
    req = GenerationRequest(prompt="One fact here. Another fact there. [00:10] noted.")
    a = eng.generate_batch([req])[0]
    b = eng.generate_batch([req])[0]
    assert a.text == b.text
    assert "[00:10]" in a.text


def test_internally_scheduled_engine_gets_whole_queue():
    """Engines with their own admission control receive all requests in one
    generate_batch call (no wave barrier)."""
    from lmrs_tpu.config import EngineConfig
    from lmrs_tpu.engine.api import GenerationRequest, GenerationResult
    from lmrs_tpu.engine.executor import MapExecutor

    calls = []

    class FakeEngine:
        schedules_internally = True

        def generate_batch(self, requests):
            calls.append(len(requests))
            return [GenerationResult(request_id=r.request_id, text="ok")
                    for r in requests]

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    ex = MapExecutor(FakeEngine(), EngineConfig(max_concurrent_requests=2))
    reqs = [GenerationRequest(prompt=f"r{i}", request_id=i) for i in range(7)]
    out = ex.run_requests(reqs)
    assert [r.request_id for r in out] == list(range(7))
    assert calls == [7]  # one call with the whole queue, not ceil(7/2) waves


def test_retry_wait_clips_to_deadline_budget():
    """The retry backoff must not sleep past a failed request's remaining
    deadline budget (the reference slept RETRY_DELAY unconditionally,
    stalling the whole wave loop): with retry_delay=30s and a 0.2 s
    budget, the run resolves in well under a second of backoff."""
    import time

    from lmrs_tpu.engine.api import GenerationResult

    class AlwaysFail:
        def generate_batch(self, requests, **kw):
            return [GenerationResult(request_id=r.request_id,
                                     finish_reason="error", error="down")
                    for r in requests]

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    ex = MapExecutor(AlwaysFail(),
                     EngineConfig(retry_attempts=3, retry_delay=30.0))
    req = GenerationRequest(prompt="p", request_id=0,
                            deadline_s=time.time() + 0.2)
    t0 = time.time()
    res = ex.run_requests([req])[0]
    assert time.time() - t0 < 5.0  # not 30s
    assert res.finish_reason == "deadline"
    assert res.error is not None  # the underlying failure stays visible


def test_retry_wait_is_interruptible_by_cancel():
    """cancel() must wake a sleeping retry backoff immediately and the
    cancelled id must resolve as cancelled, never retried."""
    import threading
    import time

    from lmrs_tpu.engine.api import GenerationResult

    class AlwaysFail:
        def generate_batch(self, requests, **kw):
            return [GenerationResult(request_id=r.request_id,
                                     finish_reason="error", error="down")
                    for r in requests]

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    ex = MapExecutor(AlwaysFail(),
                     EngineConfig(retry_attempts=5, retry_delay=30.0))
    threading.Timer(0.2, lambda: ex.cancel(0)).start()
    t0 = time.time()
    res = ex.run_requests([GenerationRequest(prompt="p", request_id=0)])[0]
    assert time.time() - t0 < 10.0  # woken, not slept out
    assert res.finish_reason == "cancelled"


def test_streaming_retry_does_not_resurrect_cancelled_request():
    """The cancel-vs-retry race: request 0 fails, the executor submits a
    retry clone, and the cancel lands while the clone is in flight — the
    final result must be 'cancelled', the clone's output discarded, and
    the clone chased through the engine's cancel hook."""
    from collections import deque

    from lmrs_tpu.engine.api import GenerationResult

    class RetryRaceEngine:
        schedules_internally = True

        def __init__(self):
            self.cancelled: set[int] = set()
            self.race_hook = lambda: None
            self.first = True

        def cancel(self, rid: int) -> None:
            self.cancelled.add(rid)

        def generate_batch(self, requests, on_result=None, on_tokens=None):
            pending = deque(requests)
            out = []

            def submit(new):
                pending.extend(new)

            while pending:
                r = pending.popleft()
                if r.request_id >= 0 and self.first:
                    self.first = False
                    res = GenerationResult(request_id=r.request_id,
                                           finish_reason="error", error="boom")
                    out.append(res)
                    if on_result:
                        on_result(res, submit)  # clone gets submitted here
                    self.race_hook()  # ...and the cancel lands right after
                    continue
                if r.request_id in self.cancelled:
                    res = GenerationResult(request_id=r.request_id,
                                           finish_reason="cancelled")
                else:
                    res = GenerationResult(request_id=r.request_id,
                                           text="resurrected!",
                                           finish_reason="stop")
                out.append(res)
                if on_result:
                    on_result(res, submit)
            return out

    eng = RetryRaceEngine()
    ex = MapExecutor(eng, EngineConfig(retry_attempts=3, retry_delay=0.0))
    eng.race_hook = lambda: ex.cancel(0)
    finals = {}
    ex.run_requests_streaming(
        [GenerationRequest(prompt="x", request_id=0)],
        lambda res, submit: finals.__setitem__(res.request_id, res))
    assert finals[0].finish_reason == "cancelled"
    assert finals[0].text != "resurrected!"
    # the live clone (negative id) was chased through the engine hook
    assert any(rid < 0 for rid in eng.cancelled), eng.cancelled


def test_streaming_no_retry_once_cancelled_before_failure_delivery():
    """When the cancel is already recorded by the time the failed result
    is delivered, no retry clone is submitted at all.  (A cancel with NO
    run in flight is a no-op — ids are reused across runs — so the cancel
    here lands from inside the running wave, before the failure.)"""
    from collections import deque

    from lmrs_tpu.engine.api import GenerationResult

    seen: list[int] = []

    class FailOnceEngine:
        schedules_internally = True

        def __init__(self):
            self.wave_start_hook = lambda: None

        def cancel(self, rid):
            pass

        def generate_batch(self, requests, on_result=None, on_tokens=None):
            self.wave_start_hook()
            pending = deque(requests)
            out = []

            def submit(new):
                pending.extend(new)

            while pending:
                r = pending.popleft()
                seen.append(r.request_id)
                res = GenerationResult(request_id=r.request_id,
                                       finish_reason="error", error="boom")
                out.append(res)
                if on_result:
                    on_result(res, submit)
            return out

    eng = FailOnceEngine()
    ex = MapExecutor(eng, EngineConfig(retry_attempts=5, retry_delay=0.0))
    ex.cancel(0)  # no run in flight: must no-op, not poison the run below
    eng.wave_start_hook = lambda: ex.cancel(0)
    finals = {}
    ex.run_requests_streaming(
        [GenerationRequest(prompt="x", request_id=0)],
        lambda res, submit: finals.__setitem__(res.request_id, res))
    assert finals[0].finish_reason == "cancelled"
    assert seen == [0]  # the original only — no clone ever dispatched


def test_streaming_cancel_terminal_even_if_attempt_succeeds():
    """An engine WITHOUT a cancel hook cannot abort in flight: when the
    cancel races a completion (here: the retry clone of a failed request
    finishes successfully), the executor must still deliver the id as
    cancelled — never resurrect an abandoned request as a success.  The
    clone's text is kept (real output, keep-partial-output convention)."""
    from collections import deque

    from lmrs_tpu.engine.api import GenerationResult

    class NoCancelHookEngine:
        schedules_internally = True

        def __init__(self):
            self.race_hook = lambda: None
            self.first = True

        def generate_batch(self, requests, on_result=None, on_tokens=None):
            pending = deque(requests)
            out = []

            def submit(new):
                pending.extend(new)

            while pending:
                r = pending.popleft()
                if self.first:
                    self.first = False
                    res = GenerationResult(request_id=r.request_id,
                                           finish_reason="error", error="boom")
                    out.append(res)
                    if on_result:
                        on_result(res, submit)
                    self.race_hook()  # cancel lands; nothing can stop the clone
                    continue
                res = GenerationResult(request_id=r.request_id,
                                       text="clone output",
                                       finish_reason="stop")
                out.append(res)
                if on_result:
                    on_result(res, submit)
            return out

    eng = NoCancelHookEngine()
    ex = MapExecutor(eng, EngineConfig(retry_attempts=3, retry_delay=0.0))
    eng.race_hook = lambda: ex.cancel(0)
    finals = {}
    ex.run_requests_streaming(
        [GenerationRequest(prompt="x", request_id=0)],
        lambda res, submit: finals.__setitem__(res.request_id, res))
    assert finals[0].finish_reason == "cancelled"
    assert finals[0].error is None
    assert finals[0].text == "clone output"  # output kept, status honest


def test_cancel_state_is_run_scoped():
    """Request ids are reused across runs on one executor (map chunks and
    reduce nodes both count from 0): a cancel in one run must not poison a
    later run's same-numbered request — its transient failure must still
    be retried to success."""
    from lmrs_tpu.engine.api import GenerationResult

    class FlakyOnce:
        def __init__(self):
            self.calls = 0

        def cancel(self, rid):
            pass

        def generate_batch(self, requests, **kw):
            self.calls += 1
            if self.calls == 2:  # run 2, attempt 1: transient failure
                return [GenerationResult(request_id=r.request_id,
                                         finish_reason="error",
                                         error="transient")
                        for r in requests]
            return [GenerationResult(request_id=r.request_id, text="ok")
                    for r in requests]

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    ex = MapExecutor(FlakyOnce(),
                     EngineConfig(retry_attempts=3, retry_delay=0.0))
    assert ex.run_requests(
        [GenerationRequest(prompt="a", request_id=0)])[0].error is None
    ex.cancel(0)  # stale: its run is already over
    res = ex.run_requests([GenerationRequest(prompt="b", request_id=0)])[0]
    assert res.error is None and res.finish_reason != "cancelled"
    assert res.text == "ok"


def test_shed_chunk_is_marked_failed_not_empty_success():
    """A content-less shed/deadline result must surface as a chunk ERROR:
    branching on res.error alone would aggregate an empty summary as a
    success and silently drop the section from the final output."""
    from lmrs_tpu.data.chunker import Chunk
    from lmrs_tpu.engine.api import GenerationResult

    class SheddingEngine:
        def generate_batch(self, requests, **kw):
            out = []
            for r in requests:
                if "drop me" in r.prompt:
                    out.append(GenerationResult(request_id=r.request_id,
                                                finish_reason="shed"))
                else:
                    out.append(GenerationResult(request_id=r.request_id,
                                                text="fine",
                                                finish_reason="stop"))
            return out

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    ex = MapExecutor(SheddingEngine(), EngineConfig(retry_delay=0.0))
    chunks = [Chunk(text="a", text_with_context="keep me"),
              Chunk(text="b", text_with_context="drop me", chunk_index=1)]
    ex.process_chunks(chunks, "{transcript}")
    assert chunks[0].error is None and chunks[0].summary == "fine"
    assert chunks[1].error is not None
    assert chunks[1].summary.startswith("[Error processing chunk:")
    assert "shed" in chunks[1].summary


def test_interrupt_is_sticky_across_remaining_backoffs():
    """interrupt() must skip EVERY remaining backoff of the run, not just
    the one in flight — a shutdown path must not sleep out the rest of a
    30s-per-retry ladder."""
    import threading
    import time

    from lmrs_tpu.engine.api import GenerationResult

    class AlwaysFail:
        def generate_batch(self, requests, **kw):
            return [GenerationResult(request_id=r.request_id,
                                     finish_reason="error", error="down")
                    for r in requests]

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    ex = MapExecutor(AlwaysFail(),
                     EngineConfig(retry_attempts=4, retry_delay=30.0))
    threading.Timer(0.2, ex.interrupt).start()
    t0 = time.time()
    res = ex.run_requests([GenerationRequest(prompt="p", request_id=0)])[0]
    # 3 backoffs of 30s would be 90s; sticky interrupt skips them all
    assert time.time() - t0 < 10.0
    assert res.finish_reason == "error"


def test_batch_path_rejects_out_of_band_request_ids():
    """The epoch guard run_requests applies (mirroring the streaming
    register): an id past the stride would land in a later run's reserved
    engine-id band."""
    ex = _executor()
    with pytest.raises(ValueError):
        ex.run_requests([GenerationRequest(prompt="p", request_id=1 << 20)])


def test_engine_never_sees_reused_request_ids_across_runs():
    """Engines keep cancel state across run boundaries (the scheduler's
    set clears at END of run, relying on globally-unique rids), so the
    executor presents epoch-offset ids: two runs with identical caller
    ids must show the engine disjoint id sets — a cancel forwarded as one
    run ends can then never alias the next run's work — while the caller
    keeps its own numbering on the results."""
    from lmrs_tpu.engine.api import GenerationResult

    seen_ids: list[set] = []

    class Recorder:
        def generate_batch(self, requests, **kw):
            seen_ids.append({r.request_id for r in requests})
            return [GenerationResult(request_id=r.request_id, text="ok")
                    for r in requests]

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    ex = MapExecutor(Recorder(), EngineConfig(retry_delay=0.0))
    for _ in range(2):
        out = ex.run_requests(
            [GenerationRequest(prompt="p", request_id=i) for i in range(3)])
        assert [r.request_id for r in out] == [0, 1, 2]  # caller space kept
    assert seen_ids[0].isdisjoint(seen_ids[1]), seen_ids


def test_executor_stamps_config_deadline():
    """EngineConfig.request_deadline_s lands on every request that doesn't
    already carry a deadline (and never overwrites an explicit one)."""
    import time

    from lmrs_tpu.engine.api import GenerationResult

    captured = []

    class Capture:
        def generate_batch(self, requests, **kw):
            captured.extend(requests)
            return [GenerationResult(request_id=r.request_id, text="ok")
                    for r in requests]

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    ex = MapExecutor(Capture(), EngineConfig(request_deadline_s=60.0))
    explicit = time.time() + 5.0
    ex.run_requests([
        GenerationRequest(prompt="a", request_id=0),
        GenerationRequest(prompt="b", request_id=1, deadline_s=explicit),
    ])
    assert captured[0].deadline_s is not None
    assert 50.0 < captured[0].deadline_s - time.time() <= 60.0
    assert captured[1].deadline_s == explicit


def test_chunk_groups_interleave_round_robin():
    """Multi-transcript pooling must admit round-robin across groups
    (VERDICT r2 item 9): FIFO admission of whole groups would starve later
    transcripts — completion skew should track transcript size, not
    submission order."""
    from lmrs_tpu.data.chunker import Chunk
    from lmrs_tpu.engine.executor import MapExecutor
    from lmrs_tpu.engine.mock import MockEngine

    class RecordingEngine(MockEngine):
        def __init__(self):
            super().__init__()
            self.seen: list[str] = []

        def generate_batch(self, requests, on_result=None, on_tokens=None):
            self.seen.extend(r.prompt.split("|")[0] for r in requests)
            return super().generate_batch(requests, on_result, on_tokens)

    eng = RecordingEngine()
    ex = MapExecutor(eng)
    groups = [
        [Chunk(text=f"A{i}", text_with_context=f"A{i}|body") for i in range(4)],
        [Chunk(text=f"B{i}", text_with_context=f"B{i}|body") for i in range(2)],
        [Chunk(text=f"C{i}", text_with_context=f"C{i}|body") for i in range(3)],
    ]
    ex.process_chunk_groups(groups, "{transcript}")
    # round-robin until groups drain: A0 B0 C0 A1 B1 C1 A2 C2 A3
    assert eng.seen == ["A0", "B0", "C0", "A1", "B1", "C1", "A2", "C2", "A3"]
    # every chunk still got its own summary (flat/results stayed aligned)
    for g in groups:
        assert all(c.summary is not None for c in g)
