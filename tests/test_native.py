"""Native C++ runtime parity tests.

The native library (native/src/lmrs_runtime.cc) re-implements the data-plane
hot loops and the KV page allocator; these tests pin its behavior to the
pure-Python reference implementations.  g++ is part of the environment, so a
build failure is a test failure, not a skip.
"""

from __future__ import annotations

import json
import random
import string
from pathlib import Path

import pytest

from lmrs_tpu.data.preprocessor import clean_text_py
from lmrs_tpu.data.tokenizer import ApproxTokenizer
from lmrs_tpu.engine.kv_cache import OutOfPages, PageAllocator
from lmrs_tpu.runtime import native


@pytest.fixture(scope="module")
def lib_ok():
    assert native.native_available(), "native runtime failed to build/load"
    return True


FIXTURE = Path("/root/reference/transcript-example.json")


CLEAN_CASES = [
    "",
    "   ",
    "hello world",
    "  hello   world  ",
    "the the the end",
    "The the plan",
    "word word, word",
    "end.Next sentence",
    "a,b then x;Y plus q:r",
    "tabs\tand\nnewlines\r\nhere",
    "one two  three   four",
    "Dr. Smith said hello.Goodbye",
    "numbers 12 12 stay? no: 12",
    "Hello!World again?Yes",
    "foofoo foo foofoo",
    "a a a a a a",
    "trailing space dedup dedup ",
    "mixed CASE case Case words",
    "punct.[bracket]",
    "unicode café café test",
    "nbsp space here",
    "wide　space",
    "emoji \U0001f600 \U0001f600 twice",
]


@pytest.mark.parametrize("text", CLEAN_CASES)
def test_clean_text_parity(lib_ok, text):
    assert native.clean_text_native(text) == clean_text_py(text)


def test_clean_text_parity_fixture(lib_ok):
    if not FIXTURE.exists():
        pytest.skip("reference fixture not mounted")
    segs = json.loads(FIXTURE.read_text())["segments"]
    for seg in segs[:2000]:
        t = seg["text"]
        assert native.clean_text_native(t) == clean_text_py(t), t


def test_clean_text_parity_random_ascii(lib_ok):
    rng = random.Random(0)
    alphabet = string.ascii_letters + string.digits + " .!?,;:\t\n_-'"
    for _ in range(500):
        t = "".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 120)))
        assert native.clean_text_native(t) == clean_text_py(t), repr(t)


def test_clean_text_batch_parity(lib_ok):
    assert native.clean_text_batch([]) == []
    batch = native.clean_text_batch(CLEAN_CASES)
    assert batch == [clean_text_py(t) for t in CLEAN_CASES]


def test_count_approx_parity(lib_ok):
    tok = ApproxTokenizer()
    cases = CLEAN_CASES + ["x", "ab", "a b c d e f g h", "word " * 100]
    for t in cases:
        assert native.count_approx_native(t) == tok.count_py(t), repr(t)


def test_count_approx_parity_fixture(lib_ok):
    if not FIXTURE.exists():
        pytest.skip("reference fixture not mounted")
    tok = ApproxTokenizer()
    segs = json.loads(FIXTURE.read_text())["segments"]
    texts = [s["text"] for s in segs[:3000]]
    batch = native.count_approx_batch(texts)
    assert batch == [tok.count_py(t) for t in texts]


def test_count_batch_matches_scalar(lib_ok):
    texts = ["", "one", "two words here", "café au lait", "x" * 1000]
    batch = native.count_approx_batch(texts)
    assert batch == [native.count_approx_native(t) for t in texts]


def test_clean_handles_non_string_segments(lib_ok):
    """``"text": null`` (and other non-strings) must drop, not crash."""
    from lmrs_tpu.data.preprocessor import preprocess_transcript

    segs = [
        {"start": 0.0, "end": 1.0, "text": None, "speaker": "A"},
        {"start": 1.0, "end": 2.0, "text": 42, "speaker": "A"},
        {"start": 2.0, "end": 3.0, "text": "kept", "speaker": "A"},
    ]
    out = preprocess_transcript(segs)
    assert len(out) == 1 and out[0]["text"] == "kept"


def test_clean_unicode_routes_to_python(lib_ok):
    """Non-ASCII goes through the Python cleaner — exact parity always."""
    cases = ["CAFÉ café plan", "ไทย ไทย",
             "café café café"]
    for t in cases:
        assert native.clean_text_native(t) == clean_text_py(t)
    assert native.clean_text_batch(cases) == [clean_text_py(t) for t in cases]


def test_count_batch_tokenizer_integration(lib_ok):
    tok = ApproxTokenizer()
    texts = ["one two three", "", "a much longer piece of text here ok"]
    assert tok.count_batch(texts) == [tok.count_py(t) for t in texts]


# ----------------------------------------------------------- page allocator


def test_allocator_parity_sequence(lib_ok):
    """Drive both allocators through an identical random op sequence —
    alloc, free, incref (a second holder delays the page's return) — and
    assert identical pages, free counts, and refcounts throughout."""
    py = PageAllocator(64)
    cc = native.NativePageAllocator(64)
    rng = random.Random(2)
    held_py: list[list[int]] = []
    held_cc: list[list[int]] = []
    for _ in range(400):
        r = rng.random()
        if r < 0.5 or not held_py:
            n = rng.randrange(1, 8)
            if n > py.free_count:
                with pytest.raises(OutOfPages):
                    py.alloc(n)
                with pytest.raises(OutOfPages):
                    cc.alloc(n)
                continue
            a, b = py.alloc(n), cc.alloc(n)
            assert a == b
            held_py.append(a)
            held_cc.append(b)
        elif r < 0.7:
            # an extra holder on a random held batch: the matching free
            # below then decrefs without returning the pages
            i = rng.randrange(len(held_py))
            py.incref(held_py[i])
            cc.incref(held_cc[i])
            py.free(held_py[i])
            cc.free(held_cc[i])
        else:
            i = rng.randrange(len(held_py))
            py.free(held_py.pop(i))
            cc.free(held_cc.pop(i))
        assert py.free_count == cc.free_count
        for p in range(64):
            assert py.refcount(p) == cc.refcount(p), p


def test_allocator_double_free_parity(lib_ok):
    """Both allocators must reject a double-free identically — and leave
    the pool untouched when a batch contains one bad id."""
    py = PageAllocator(16)
    cc = native.NativePageAllocator(16)
    pa, ca = py.alloc(3), cc.alloc(3)
    assert pa == ca
    py.free(pa)
    cc.free(ca)
    for alloc_ in (py, cc):
        with pytest.raises(ValueError):
            alloc_.free([pa[0]])
    live_py, live_cc = py.alloc(1), cc.alloc(1)
    # batch with one live + one free id: rejected atomically on both sides
    with pytest.raises(ValueError):
        py.free(live_py + [pa[1]])
    with pytest.raises(ValueError):
        cc.free(live_cc + [ca[1]])
    assert py.refcount(live_py[0]) == cc.refcount(live_cc[0]) == 1
    assert py.free_count == cc.free_count
    # incref of a free page is equally rejected (pa[1] stayed free: the
    # rejected batch above must not have touched it)
    with pytest.raises(ValueError):
        py.incref([pa[1]])
    with pytest.raises(ValueError):
        cc.incref([ca[1]])


def test_allocator_reserved_page(lib_ok):
    cc = native.NativePageAllocator(8)
    got = cc.alloc(7)
    assert 0 not in got
    assert sorted(got) == list(range(1, 8))
    with pytest.raises(OutOfPages):
        cc.alloc(1)
    cc.free(got)
    assert cc.free_count == 7
    with pytest.raises(ValueError):
        cc.free([0])
    with pytest.raises(ValueError):
        cc.free([8])
    with pytest.raises(ValueError):
        native.NativePageAllocator(1)


def test_paged_cache_uses_native(lib_ok):
    from lmrs_tpu.config import ModelConfig
    from lmrs_tpu.engine.kv_cache import PagedKVCache
    from lmrs_tpu.runtime.native import NativePageAllocator

    cache = PagedKVCache(ModelConfig(), num_pages=16, page_size=8,
                         max_pages_per_slot=4)
    assert isinstance(cache.allocator, NativePageAllocator)
    seq = cache.open_sequence(20)
    assert len(seq.pages) == 3
    cache.close_sequence(seq)
    assert cache.allocator.free_count == 15
