"""Chaos soak: seeded random workloads x seeded fault plans, audited.

The tentpole robustness gate (SURVEY.md §5.3 "no fault injection"): every
scenario drives real requests through the MapExecutor into an engine while
a deterministic FaultPlan (lmrs_tpu/testing/faults.py) fires OutOfPages
pressure, scheduler step faults, engine batch faults, and prefix-cache
insertion faults — then asserts the system-level invariants:

* every submitted request terminates EXACTLY once, with a valid finish
  reason (``stop|length|error|cancelled|deadline|shed``);
* the scheduler's invariant auditor is clean after every scenario — page
  conservation, refcount balance, radix-tree structure
  (``ContinuousScheduler.audit``);
* identical seeds x identical plans reproduce identical outcomes;
* the fault plane disarmed is a byte-for-byte no-op (greedy A/B).

Scenario seeds and plans are PINNED — the tier-1 chaos gate replays them
verbatim.  Both engine arms run: MockEngine (no-device) and a CPU
JaxEngine with a real continuous scheduler under page pressure.
"""

from __future__ import annotations

import random
import time

import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.executor import MapExecutor
from lmrs_tpu.engine.jax_engine import JaxEngine
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.testing import faults
from lmrs_tpu.testing.faults import FaultPlan

VALID_REASONS = {"stop", "length", "error", "cancelled", "deadline",
                 "shed", "wedged"}

_WORDS = ("alpha bravo charlie delta echo foxtrot golf hotel india "
          "juliet kilo lima mike november oscar papa").split()


def chaos_model() -> ModelConfig:
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


@pytest.fixture(scope="module")
def jax_engine():
    # small page pool (vs. the 16-page-per-slot worst case) so real — not
    # only injected — OutOfPages pressure occurs; decode_block 4 gives the
    # sweeps frequent block boundaries
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=64, max_batch_slots=2, seed=0,
                                 decode_block=4, page_size=16, num_pages=20),
                    chaos_model())
    yield eng
    eng.shutdown()


def make_workload(rng: random.Random, n: int,
                  deadlines: bool = False,
                  greedy: bool = False) -> list[GenerationRequest]:
    reqs = []
    for i in range(n):
        prompt = f"chaos {i} " + " ".join(
            rng.choice(_WORDS) for _ in range(rng.randint(2, 24)))
        req = GenerationRequest(
            prompt=prompt, request_id=i,
            temperature=0.0 if greedy else rng.choice((0.0, 0.8)),
            max_new_tokens=rng.randint(2, 16))
        if deadlines and rng.random() < 0.4:
            # a mix of already-expired, tight, and comfortable budgets
            req.deadline_s = time.time() + rng.choice((-1.0, 0.05, 30.0))
        reqs.append(req)
    return reqs


def soak(engine, sched, seed: int, plan_faults: list,
         deadlines: bool = False, retries: int = 3, greedy: bool = False,
         retry_delay: float = 0.01):
    """One pinned scenario: run a seeded workload under a seeded plan
    through the executor's retry machinery, then assert the termination
    and auditor invariants."""
    rng = random.Random(seed)
    reqs = make_workload(rng, rng.randint(3, 6), deadlines, greedy)
    ex = MapExecutor(engine, EngineConfig(
        retry_attempts=retries, retry_delay=retry_delay))
    with faults.injected(FaultPlan(seed=seed, faults=plan_faults)):
        results = ex.run_requests(reqs)
    # no result lost or duplicated, order preserved
    assert [r.request_id for r in results] == [r.request_id for r in reqs]
    for res in results:
        assert res.finish_reason in VALID_REASONS, res
        if res.finish_reason in ("stop", "length", "cancelled", "shed"):
            # "deadline" may carry an error when a FAILED request's budget
            # expired before its retry (executor clip); the others never do
            assert res.error is None, res
    if sched is not None:
        violations = sched.audit()
        assert violations == [], violations
    return results


# Pinned fault plans (the seed x plan grid is the tier-1 chaos gate's
# contract — do not rotate values without updating the gate's rationale).
JAX_PLANS = {
    "oom": [{"site": "kv_cache.allocate", "p": 0.35, "max_fires": 6}],
    "step": [{"site": "scheduler.step", "at": [4], "max_fires": 1}],
    "insert": [{"site": "prefix_cache.insert", "p": 0.6, "max_fires": 8}],
    "combo": [{"site": "kv_cache.allocate", "p": 0.25, "max_fires": 4},
              {"site": "scheduler.step", "at": [7], "max_fires": 1},
              {"site": "prefix_cache.insert", "p": 0.5, "max_fires": 4},
              {"site": "engine.batch", "at": [1], "max_fires": 1}],
}

MOCK_PLANS = {
    "batch": [{"site": "engine.batch", "at": [1], "max_fires": 1}],
    "batch_p": [{"site": "engine.batch", "p": 0.5, "max_fires": 2}],
}


# 8 jax + 12 mock = 20 pinned scenarios.  The jax arm carries the real
# scheduler/pool machinery (each scenario ~1-3 s warm); the mock arm is
# near-free, so it carries the wider seed sweep — tier-1 wall-clock stays
# bounded without thinning coverage.
@pytest.mark.parametrize("seed", [11, 23])
@pytest.mark.parametrize("plan", sorted(JAX_PLANS))
def test_chaos_jax(jax_engine, seed, plan):
    soak(jax_engine, jax_engine._scheduler, seed, JAX_PLANS[plan],
         deadlines=(plan == "combo"))


@pytest.mark.parametrize("seed", [3, 5, 9, 17, 25, 33])
@pytest.mark.parametrize("plan", sorted(MOCK_PLANS))
def test_chaos_mock(seed, plan):
    soak(MockEngine(seed=0), None, seed, MOCK_PLANS[plan], deadlines=True)


def test_chaos_step_fault_recovery_is_deterministic(jax_engine):
    """A scheduler-step fault on the FIRST iteration kills the whole run;
    the executor retries; the engine must survive with a clean pool and
    produce the same greedy text a fault-free run produces."""
    sched = jax_engine._scheduler
    plan = [{"site": "scheduler.step", "at": [1], "max_fires": 1}]
    baseline = soak(jax_engine, sched, 99, [], greedy=True)
    faulted = soak(jax_engine, sched, 99, plan, greedy=True)
    assert [(r.request_id, r.finish_reason, r.text) for r in baseline] == \
        [(r.request_id, r.finish_reason, r.text) for r in faulted]
    assert sched.audit() == []


def test_chaos_fault_inside_mixed_step(jax_engine):
    """A fault landing INSIDE the mixed-dispatch window (ISSUE 11): the
    workload staggers budgets so a long prompt is admitted while another
    request still decodes — its prefill rides fused mixed steps — and the
    fault plan fires OutOfPages during the mixed capacity growth plus one
    scheduler-step fault mid-run.  Decode rows survive (stall/preempt,
    then run-recovery + executor retry — every request completes with a
    valid reason, token-identical to the fault-free run) and the
    interrupted prefill slice retries; auditor clean."""
    sched = jax_engine._scheduler
    assert sched._mixed, "mixed dispatch must be armed on the chaos engine"

    def reqs():
        return [
            GenerationRequest(prompt="mixed chaos steady", request_id=0,
                              temperature=0.0, max_new_tokens=24),
            GenerationRequest(prompt="early finisher", request_id=1,
                              temperature=0.0, max_new_tokens=4),
            # admitted when slot 1 frees, while request 0 still decodes:
            # its ~120-token prompt prefills via mixed slices
            GenerationRequest(prompt="late long admission words " * 5,
                              request_id=2, temperature=0.0,
                              max_new_tokens=6),
        ]

    def run(plan_faults):
        ex = MapExecutor(jax_engine, EngineConfig(retry_attempts=3,
                                                  retry_delay=0.01))
        before = sched.metrics["mixed_dispatches"]
        with faults.injected(FaultPlan(seed=91, faults=plan_faults)):
            out = ex.run_requests(reqs())
        assert sched.metrics["mixed_dispatches"] > before, \
            "scenario never entered the mixed window"
        for res in out:
            assert res.finish_reason in VALID_REASONS, res
        assert sched.audit() == []
        return [(r.request_id, r.finish_reason, r.text) for r in out]

    baseline = run([])
    # OutOfPages pressure inside mixed capacity growth: decode rows
    # stall/preempt but never error, the prefill slice is re-dispatched
    faulted = run([{"site": "kv_cache.allocate", "p": 0.4, "max_fires": 6}])
    assert faulted == baseline
    # a step fault killing an iteration mid-mix: pool recovery + executor
    # retry reproduce the same greedy output
    faulted = run([{"site": "scheduler.step", "at": [6], "max_fires": 1}])
    assert faulted == baseline


def test_chaos_identical_seeds_identical_outcomes():
    """Same workload seed + same plan seed => identical outcome tuples
    (the replayability contract chaos triage depends on)."""
    def once():
        return [(r.request_id, r.finish_reason, r.text, r.completion_tokens)
                for r in soak(MockEngine(seed=0), None, 29,
                              MOCK_PLANS["batch_p"])]

    assert once() == once()


def test_chaos_jax_identical_seeds_identical_outcomes(jax_engine):
    """Greedy replay on the live engine: insert faults perturb the cache,
    never the tokens — two identical scenario runs match exactly."""
    def once():
        return [(r.request_id, r.finish_reason, r.text)
                for r in soak(jax_engine, jax_engine._scheduler, 31,
                              JAX_PLANS["insert"], greedy=True)]

    assert once() == once()


def test_fault_plan_object_reinstalls_replay_identically():
    """All mutable evaluation state (occurrence counters, fire counts,
    RNG streams) lives on the injector, so installing the SAME plan
    object repeatedly replays exactly — the shape a triage harness takes
    when it parses LMRS_FAULT_PLAN once and reruns per scenario."""
    from lmrs_tpu.testing.faults import InjectedFault

    plan = FaultPlan(seed=7, faults=[
        {"site": "s", "at": [1], "max_fires": 1},
        {"site": "q", "p": 0.5, "max_fires": 2}])
    runs = []
    for _ in range(3):
        with faults.injected(plan) as inj:
            outcomes = []
            for site in ("s", "q"):
                for _ in range(6):
                    try:
                        faults.fire(site)
                        outcomes.append(0)
                    except InjectedFault:
                        outcomes.append(1)
            runs.append((outcomes, list(inj.fires)))
    assert runs[0] == runs[1] == runs[2]
    assert runs[0][0][0] == 1  # the at=[1] spec fired on every install


def test_spec_reinstall_is_idempotent_per_process():
    """make_engine re-applies the env-derived fault_plan knob on every
    engine construction: re-arming the SAME spec string must keep the
    live injector (occurrence counters, max_fires state) — 'fire once'
    means once per process, not once per engine built."""
    from lmrs_tpu.testing.faults import InjectedFault

    spec = '{"faults": [{"site": "z", "at": [1], "max_fires": 1}]}'
    try:
        inj1 = faults.install_spec(spec)
        with pytest.raises(InjectedFault):
            faults.fire("z")
        assert faults.install_spec(spec) is inj1  # second make_engine
        faults.fire("z")  # max_fires already spent: must NOT fire again
        # a DIFFERENT spec replaces the injector with fresh state
        assert faults.install_spec(spec + " ") is not inj1
    finally:
        faults.uninstall()


def test_fault_plane_disabled_is_token_identical(jax_engine):
    """The acceptance A/B: with LMRS_FAULT_PLAN unset (no plan installed)
    and with a plan installed whose sites never fire, the greedy output is
    token-identical — the injection sites cost nothing when disarmed."""
    assert faults.active() is None  # tier-1 runs with the env unset

    def run():
        return jax_engine.generate_batch([GenerationRequest(
            prompt="fault plane ab check", request_id=0,
            temperature=0.0, max_new_tokens=12)])[0]

    base = run()
    with faults.injected(FaultPlan(seed=1, faults=[
            {"site": "no.such.site", "at": [1]}])):
        armed = run()
    after = run()
    assert base.text == armed.text == after.text
    assert base.finish_reason == armed.finish_reason == after.finish_reason


# ------------------------------------------------------- hang survival


def test_chaos_wedge_stall_recovers_token_identical(jax_engine,
                                                    monkeypatch):
    """Hang-survival soak (ISSUE 14): a ``scheduler.heartbeat`` stall
    wedges the dispatch loop mid-run; the watchdog abandons it (wedged
    results carry the error mark), the executor's retry waits out the
    transient stall, and the scenario completes with every greedy output
    token-identical to a fault-free run and the auditor clean — a wedge
    is a bounded, retryable failure, not a hang."""
    sched = jax_engine._scheduler
    assert jax_engine._runner is not None  # watchdog armed by default
    # baseline runs BEFORE the tiny threshold is armed: a cold engine's
    # first iterations legitimately exceed 0.3s (first executions of
    # freshly compiled programs) and must not false-positive
    baseline = soak(jax_engine, sched, 99, [], greedy=True)
    assert jax_engine._runner.wait_idle(30.0)
    monkeypatch.setenv("LMRS_WATCHDOG_S", "0.3")
    fires = sched.metrics["watchdog_fires"]
    plan = [{"site": "scheduler.heartbeat", "at": [2], "action": "stall",
             "stall_s": 1.0, "max_fires": 1}]
    # the retry budget outlasts the stall AND the abandoned run's drain
    # (it keeps computing — and compiling post-stall shapes — after the
    # stall clears, and the engine stays fail-fast degraded until it
    # finishes): generous attempts x delay, the FIRST retry on the
    # recovered engine succeeds
    faulted = soak(jax_engine, sched, 99, plan, greedy=True,
                   retries=8, retry_delay=2.0)
    # >= and not ==: post-stall interleaving can compile novel shapes
    # whose first executions run close to the deliberately tiny test
    # threshold — an extra fire is retried away, never an error
    assert sched.metrics["watchdog_fires"] >= fires + 1
    assert [(r.request_id, r.finish_reason, r.text) for r in baseline] == \
        [(r.request_id, r.finish_reason, r.text) for r in faulted]
    assert jax_engine._runner.wait_idle(30.0)
    assert sched.audit() == []


def test_chaos_wedge_watchdog_postmortem(jax_engine, monkeypatch,
                                         tmp_path):
    """The wedge scenario with the flight recorder armed: the watchdog's
    declaration freezes a schema-valid ``watchdog`` postmortem before the
    sweep rewrites any counters."""
    from lmrs_tpu.obs import validate_postmortem_file

    monkeypatch.setenv("LMRS_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.setenv("LMRS_POSTMORTEM_MIN_S", "0")
    # warm first (same reason as the recovery test above: the tiny
    # threshold must only ever see warm iterations), then arm
    soak(jax_engine, jax_engine._scheduler, 11, [], greedy=True)
    assert jax_engine._runner.wait_idle(30.0)
    monkeypatch.setenv("LMRS_WATCHDOG_S", "0.3")
    plan = [{"site": "scheduler.heartbeat", "at": [2], "action": "stall",
             "stall_s": 1.0, "max_fires": 1}]
    soak(jax_engine, jax_engine._scheduler, 11, plan, greedy=True,
         retries=8, retry_delay=2.0)
    dumps = sorted(tmp_path.glob("postmortem-watchdog-*.json"))
    assert dumps, "wedge produced no watchdog postmortem"
    doc = validate_postmortem_file(dumps[0])
    assert doc["reason"] == "watchdog"
    assert doc["extra"]["undelivered"] >= 1
    assert jax_engine._runner.wait_idle(30.0)
    assert jax_engine._scheduler.audit() == []


# ------------------------------------------------------ deadline contract


def test_deadline_shed_before_prefill(jax_engine):
    """An unadmittable request (expired budget) is shed with ZERO engine
    work: no prefill tokens spent, finish_reason='shed', empty text."""
    sched = jax_engine._scheduler
    before = sched.metrics["prefill_tokens"]
    shed_before = sched.metrics["shed"]
    res = jax_engine.generate_batch([GenerationRequest(
        prompt="far too late", request_id=0, temperature=0.0,
        max_new_tokens=8, deadline_s=time.time() - 1.0)])[0]
    assert res.finish_reason == "shed"
    assert res.error is None
    assert res.completion_tokens == 0 and res.text == ""
    assert sched.metrics["prefill_tokens"] == before
    assert sched.metrics["shed"] == shed_before + 1
    assert sched.audit() == []


def test_deadline_expires_in_flight_within_a_block(jax_engine):
    """An in-flight request whose deadline passes finishes with
    finish_reason='deadline' at the next block boundary, keeping the
    tokens generated so far.  A fault-plane STALL at scheduler iteration 3
    burns the budget while the request provably holds a slot (one decode
    block is already recorded by then), so expiry lands mid-flight
    deterministically, regardless of machine speed."""
    sched = jax_engine._scheduler
    # warm the compiled shapes AND the observed-TTFT floor so the 0.4 s
    # budget is comfortably admittable (the estimate is the fastest
    # observed TTFT; the second warmup runs on compiled shapes)
    for rid in (900, 901):
        jax_engine.generate_batch([GenerationRequest(
            prompt="warmup", request_id=rid, temperature=0.0,
            max_new_tokens=8)])
    assert sched._ttft_min < 0.4, sched._ttft_min
    dl_before = sched.metrics["deadline_exceeded"]
    plan = FaultPlan(faults=[{"site": "scheduler.step", "at": [3],
                              "action": "stall", "stall_s": 0.7}])
    with faults.injected(plan):
        res = jax_engine.generate_batch([GenerationRequest(
            prompt="expire me in flight", request_id=0,
            temperature=0.0, max_new_tokens=64,
            deadline_s=time.time() + 0.4)])[0]
    assert res.finish_reason == "deadline", res
    assert res.error is None
    # expiry was swept at the block boundary right after the stall: the
    # blocks already recorded are kept, the remaining budget is abandoned
    assert 1 <= res.completion_tokens < 64
    assert sched.metrics["deadline_exceeded"] == dl_before + 1
    assert sched.audit() == []


# ------------------------------------------------------- flight recorder


def test_postmortem_on_dispatch_fault(jax_engine, monkeypatch, tmp_path):
    """A scheduler-step fault mid-run must leave a schema-valid
    postmortem behind (spans + metrics frozen BEFORE pool recovery),
    while the run itself still degrades and the auditor stays clean —
    the flight-recorder arm of the acceptance criteria."""
    from lmrs_tpu.obs import validate_postmortem_file

    monkeypatch.setenv("LMRS_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.setenv("LMRS_POSTMORTEM_MIN_S", "0")
    soak(jax_engine, jax_engine._scheduler, 11, JAX_PLANS["step"])
    dumps = sorted(tmp_path.glob("postmortem-dispatch_fault-*.json"))
    assert dumps, "dispatch fault produced no postmortem"
    doc = validate_postmortem_file(dumps[0])
    assert doc["reason"] == "dispatch_fault"
    assert "error" in doc["extra"]
    assert doc["metrics"].get("decode_dispatches", 0) >= 0
    assert jax_engine._scheduler.audit() == []


def test_postmortem_on_inflight_deadline_expiry(jax_engine, monkeypatch,
                                                tmp_path):
    """The in-flight deadline-expiry chaos scenario with the storm
    threshold armed at 1: the sweep that reaps the expired slot dumps a
    deadline_storm postmortem (same stall-driven shape as
    test_deadline_expires_in_flight_within_a_block), auditor clean."""
    from lmrs_tpu.obs import validate_postmortem_file

    sched = jax_engine._scheduler
    for rid in (910, 911):  # warm shapes + the observed-TTFT floor
        jax_engine.generate_batch([GenerationRequest(
            prompt="warmup storm", request_id=rid, temperature=0.0,
            max_new_tokens=8)])
    assert sched._ttft_min < 0.4, sched._ttft_min
    monkeypatch.setenv("LMRS_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.setenv("LMRS_POSTMORTEM_MIN_S", "0")
    monkeypatch.setenv("LMRS_DEADLINE_STORM", "1")
    plan = FaultPlan(faults=[{"site": "scheduler.step", "at": [3],
                              "action": "stall", "stall_s": 0.7}])
    with faults.injected(plan):
        res = jax_engine.generate_batch([GenerationRequest(
            prompt="expire me into the recorder", request_id=0,
            temperature=0.0, max_new_tokens=64,
            deadline_s=time.time() + 0.4)])[0]
    assert res.finish_reason == "deadline", res
    dumps = sorted(tmp_path.glob("postmortem-deadline_storm-*.json"))
    assert dumps, "in-flight expiry produced no postmortem"
    doc = validate_postmortem_file(dumps[0])
    assert doc["extra"]["expired_this_sweep"] >= 1
    assert sched.audit() == []


def test_postmortem_disabled_without_dir(jax_engine, monkeypatch, tmp_path):
    """With LMRS_POSTMORTEM_DIR unset the recorder is a strict no-op —
    the existing chaos grid must not start writing files."""
    monkeypatch.delenv("LMRS_POSTMORTEM_DIR", raising=False)
    soak(jax_engine, jax_engine._scheduler, 23, JAX_PLANS["step"])
    assert not list(tmp_path.glob("postmortem-*.json"))


def test_static_scheduler_sheds_expired_at_admission():
    """The static scheduler also honors admission shedding (it cannot
    expire in flight — no host sync inside its on-device while_loop; see
    docs/ROBUSTNESS.md scheduler coverage)."""
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="static",
                                 max_tokens=4, max_batch_slots=1, seed=0),
                    chaos_model())
    try:
        res = eng.generate_batch([GenerationRequest(
            prompt="late", request_id=0, max_new_tokens=4,
            deadline_s=time.time() - 1.0)])[0]
        assert res.finish_reason == "shed" and res.text == ""
        ok = eng.generate_batch([GenerationRequest(
            prompt="fine", request_id=1, temperature=0.0,
            max_new_tokens=4)])[0]
        assert ok.finish_reason in ("stop", "length")
    finally:
        eng.shutdown()


def test_deadline_mock_sheds_expired():
    res = MockEngine().generate_batch([GenerationRequest(
        prompt="late", request_id=4, deadline_s=time.time() - 0.1)])[0]
    assert res.finish_reason == "shed" and res.text == ""


# ------------------------------------------- disaggregated handoff chaos


@pytest.fixture(scope="module")
def disagg_cluster():
    """In-process prefill-role + decode-role EngineHTTPServers over REAL
    jax continuous schedulers, behind a pool-aware router — the AUDITED
    arm of the handoff chaos scenarios: every scenario ends with
    ``scheduler.audit()`` clean on both pods (pinned-for-export pages
    accounted, zero leaks, refcounts balanced) after the orphan sweep.
    The cross-process mock arm lives in tests/test_handoff.py."""
    from lmrs_tpu.serving.router import RouterEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    cfg = EngineConfig(backend="jax", scheduler="continuous", max_tokens=64,
                       max_batch_slots=2, seed=0, decode_block=4,
                       page_size=16, num_pages=48, handoff_ttl_s=30.0)
    pre_eng = JaxEngine(cfg, chaos_model())
    dec_eng = JaxEngine(cfg, chaos_model())
    pre = EngineHTTPServer(pre_eng, port=0, role="prefill",
                           handoff_ttl_s=30.0)
    dec = EngineHTTPServer(dec_eng, port=0, role="decode",
                           handoff_ttl_s=30.0)
    pre.start_background()
    dec.start_background()
    router = RouterEngine([], prefill_hosts=[f"127.0.0.1:{pre.port}"],
                          decode_hosts=[f"127.0.0.1:{dec.port}"])
    # colocated greedy baseline over the SAME weights, computed with the
    # fault plane disarmed (also proves a prefill-role pod serves plain
    # requests to completion — the colocated-fallback invariant)
    colo = RouterEngine([f"127.0.0.1:{pre.port}"])
    assert faults.active() is None
    baseline = colo.generate_batch([_handoff_req(0)])[0]
    assert baseline.error is None and baseline.completion_tokens > 1
    yield pre, dec, router, baseline.text
    for r in (router, colo):
        r.shutdown()
    for s in (pre, dec):
        s.shutdown()
    pre_eng.shutdown()
    dec_eng.shutdown()


def _handoff_req(rid: int) -> GenerationRequest:
    return GenerationRequest(
        prompt="chaos handoff probe alpha bravo charlie delta echo",
        request_id=rid, temperature=0.0, max_new_tokens=10)


def _settle_and_audit(pre, dec) -> None:
    """Close a scenario: orphan-sweep far past every ticket deadline,
    then require both pods' auditors clean — no pinned-page leaks, page
    conservation and refcounts balanced across the transaction."""
    pre.sweep_handoffs(now=time.time() + 3600.0)
    dec.sweep_handoffs(now=time.time() + 3600.0)
    assert pre.engine._scheduler.pinned_handoffs() == {}
    assert pre.engine._scheduler.audit() == []
    assert dec.engine._scheduler.audit() == []


def test_chaos_handoff_baseline_disagg_token_identical(disagg_cluster):
    """Fault-free two-tier flow on the jax pods: token-identical to the
    colocated baseline, pin released by the ack, auditors clean."""
    pre, dec, router, want = disagg_cluster
    res = router.generate_batch([_handoff_req(1)])[0]
    assert res.error is None and res.text == want
    assert router._handoffs >= 1
    assert pre.engine._scheduler.pinned_handoffs() == {}  # acked
    _settle_and_audit(pre, dec)


def test_chaos_handoff_transfer_fault_mid_payload(disagg_cluster):
    """Transfer dies mid-payload: marked import failure, router re-prefills
    colocated, request completes identically; the un-acked ticket's pages
    come back through the orphan sweep."""
    pre, dec, router, want = disagg_cluster
    orphaned_before = pre.engine._scheduler.metrics["handoff_orphaned_pages"]
    fallbacks = router._handoff_fallbacks
    with faults.injected(FaultPlan(seed=41, faults=[
            {"site": "handoff.transfer", "at": [1], "max_fires": 1}])):
        res = router.generate_batch([_handoff_req(2)])[0]
    assert res.error is None and res.text == want
    assert router._handoff_fallbacks == fallbacks + 1
    assert pre.engine._scheduler.pinned_handoffs() != {}  # never acked
    _settle_and_audit(pre, dec)
    assert (pre.engine._scheduler.metrics["handoff_orphaned_pages"]
            > orphaned_before)


def test_chaos_handoff_decode_pod_down_after_export(disagg_cluster):
    """The decode pod dies between export and import (connect fault on
    the decode leg — occurrence 2: the prefill leg was 1): the router
    re-prefills on a surviving host and the request completes; the
    pinned pages orphan-sweep."""
    pre, dec, router, want = disagg_cluster
    fallbacks = router._handoff_fallbacks
    with faults.injected(FaultPlan(seed=43, faults=[
            {"site": "router.connect", "at": [2], "max_fires": 1}])):
        res = router.generate_batch([_handoff_req(3)])[0]
    assert res.error is None and res.text == want
    assert router._handoff_fallbacks == fallbacks + 1
    _settle_and_audit(pre, dec)
    # the connect fault marked the decode host down; let the next wave's
    # probe re-admit it so later scenarios still disaggregate
    for h in router.hosts:
        h.healthy = True


def test_chaos_handoff_ack_lost_duplicate_import(disagg_cluster):
    """Both ack attempts vanish: the request still completes (acks are
    best-effort; the orphan sweep is the backstop), the pages stay pinned,
    and RE-DELIVERING the same ticket to the decode pod is idempotently
    rejected (409) instead of double-importing."""
    import json as _json
    import urllib.error
    import urllib.request

    pre, dec, router, want = disagg_cluster
    with faults.injected(FaultPlan(seed=47, faults=[
            {"site": "handoff.ack", "every": 1, "max_fires": 2}])):
        res = router.generate_batch([_handoff_req(4)])[0]
    assert res.error is None and res.text == want
    pinned = pre.engine._scheduler.pinned_handoffs()
    assert pinned, "lost ack must leave the export pinned"
    # the live (un-consumed) ticket: re-deliver it to the decode pod
    tid = next(t for t, r in pre.handoff._tickets.items()
               if not r["consumed"])
    body = _json.dumps({
        "messages": [{"role": "user", "content": _handoff_req(4).prompt}],
        "max_tokens": 10, "temperature": 0.0,
        "handoff": {"ticket": tid,
                    "source": f"127.0.0.1:{pre.port}"}}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{dec.port}/v1/chat/completions", data=body,
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 409
    _settle_and_audit(pre, dec)


def test_chaos_handoff_ticket_expiry_orphan_sweep(disagg_cluster):
    """A ticket published but never followed (the router died between
    legs): the orphan sweep reclaims the pinned pages at the deadline and
    later fetches answer 410 Gone."""
    import json as _json
    import urllib.error
    import urllib.request

    pre, dec, _router, _want = disagg_cluster
    body = _json.dumps({
        "messages": [{"role": "user", "content": _handoff_req(5).prompt}],
        "max_tokens": 10, "temperature": 0.0, "handoff": True}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{pre.port}/v1/chat/completions", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        data = _json.loads(r.read())
    assert data["object"] == "handoff.ticket"
    tid = data["handoff"]["ticket"]
    assert pre.engine._scheduler.pinned_handoffs()
    released = pre.sweep_handoffs(now=time.time() + 3600.0)
    assert released >= 1
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"http://127.0.0.1:{pre.port}/v1/handoff/{tid}", timeout=10)
    assert ei.value.code == 410
    _settle_and_audit(pre, dec)


# ------------------------------------------------- auditor negative cases


def _ensure_cached_prefix(engine) -> list[int]:
    """Make sure the prefix cache retains at least one page — the tests
    below corrupt cache state and must not depend on earlier soak tests
    having run (any -k selection or reordering would otherwise break)."""
    sched = engine._scheduler
    if not sched._prefix_cache.retained_pages():
        engine.generate_batch([GenerationRequest(
            prompt="seed the prefix cache with a long enough prompt " * 2,
            request_id=800, temperature=0.0, max_new_tokens=2)])
    pages = sched._prefix_cache.retained_pages()
    assert pages, "a full-page prompt must populate the cache"
    return pages


def test_audit_reports_leaked_page(jax_engine):
    """The auditor must be PROVEN able to fail: a page allocated outside
    any accounted owner is a leak it reports; releasing it restores a
    clean report."""
    sched = jax_engine._scheduler
    assert sched.audit() == []
    leaked = sched.cache.allocator.alloc(1)
    violations = sched.audit()
    assert any("leaked" in v for v in violations), violations
    sched.cache.allocator.free(leaked)
    assert sched.audit() == []


def test_audit_reports_unbalanced_refcount(jax_engine):
    """A stray incref on a cache-retained page shows as a refcount the
    accounted holders cannot explain."""
    sched = jax_engine._scheduler
    retained = _ensure_cached_prefix(jax_engine)
    assert sched.audit() == []
    victim = retained[0]
    sched.cache.allocator.incref([victim])
    violations = sched.audit()
    assert any("unbalanced" in v for v in violations), violations
    sched.cache.allocator.free([victim])
    assert sched.audit() == []


def test_audit_reports_tree_corruption(jax_engine):
    """A radix node whose page list disagrees with its token span is a
    structural violation."""
    sched = jax_engine._scheduler
    _ensure_cached_prefix(jax_engine)
    pc = sched._prefix_cache
    node = next(iter(pc.root.children.values()))
    saved = node.tokens
    node.tokens = saved[:-1]  # no longer a page multiple
    try:
        violations = sched.audit()
        assert any("tokens" in v or "pages" in v for v in violations), \
            violations
    finally:
        node.tokens = saved
    assert sched.audit() == []


def test_audit_reports_double_finish(jax_engine):
    """Termination-exactly-once: a second result record for one id is
    counted and reported."""
    from lmrs_tpu.engine.api import GenerationResult

    sched = jax_engine._scheduler
    assert sched.audit() == []
    results = {}
    sched._record_result(results, GenerationResult(request_id=7))
    sched._record_result(results, GenerationResult(request_id=7))
    try:
        violations = sched.audit()
        assert any("terminat" in v for v in violations), violations
    finally:
        sched._audit_double_finish = 0
    assert sched.audit() == []


# ------------------------------------------------- durable-job SIGKILL chaos
# ISSUE 7 acceptance: a job SIGKILL'd mid-map and mid-reduce resumes from
# the write-ahead journal to a greedy final summary token-identical to an
# uninterrupted run, with scheduler.audit() clean — plus the torn-tail and
# duplicate-replay crash-window variants.  The child process
# (tests/_job_worker.py) runs one durable job; the parent paces its
# journal with a journal.append stall plan, watches the WAL grow, and
# kills at the exact unit of work under test.
#
# Two arms, two halves of the contract:
#
# * MOCK — deterministic, batch-invariant text: the strict token-identity
#   assertions live here, for kills mid-map and mid-reduce plus the
#   torn-tail / duplicate-replay variants.
# * JAX  — the real continuous scheduler: resume-correctness and
#   ``scheduler.audit()`` clean after every kill-resume.  The chaos
#   geometry runs CONTENT-FREE random-init weights, whose near-uniform
#   logits make greedy argmax knife-edge sensitive to engine history
#   (slot/free-list order shifts prefill numerics by ulps) — ANY
#   recompute on a differently-warmed engine is not bit-stable on this
#   model (a real checkpoint's logit margins dwarf the ulp noise; the
#   mock arm carries the identity contract for resumes that recompute).
#   The kill-before-done scenario — root node durable, terminal record
#   not — recomputes NOTHING, so it asserts strict token identity on
#   the real engine: the journal alone carries the complete result.

import json
import os
import shutil
import signal
import subprocess
import sys
from pathlib import Path

sys.path.insert(0, os.path.dirname(__file__))
import _job_worker as jw  # noqa: E402 - shared parent/child job configs

from lmrs_tpu.jobs import journal as jl  # noqa: E402
from lmrs_tpu.jobs.manager import JobManager  # noqa: E402


def _run_uninterrupted(backend: str, tmp_dir, engine=None):
    """One uninterrupted durable job: the token-identity reference."""
    eng = engine or jw.build_engine(backend)
    jm = JobManager(eng, tmp_dir, config=jw.job_pipeline_config(backend),
                    start_worker=False)
    job = jm.submit(jw.job_transcript())
    jm.run_job(job)
    jm.shutdown()
    assert job.status == "done", job.error
    if engine is None and backend == "jax":
        assert eng._scheduler.audit() == []
        eng.shutdown()
    return job


@pytest.fixture(scope="module")
def mock_job_baseline(tmp_path_factory):
    d = tmp_path_factory.mktemp("job_chaos_mock_ref")
    job = _run_uninterrupted("mock", d, engine=jw.build_engine("mock"))
    assert job.n_chunks >= 4 and job.reduce_nodes_done >= 3
    return {"jid": job.job_id, "n_chunks": job.n_chunks,
            "n_nodes": job.reduce_nodes_done,
            "summary": job.result["summary"]}


@pytest.fixture(scope="module")
def jax_job_baseline(tmp_path_factory):
    d = tmp_path_factory.mktemp("job_chaos_jax_ref")
    job = _run_uninterrupted("jax", d)
    assert job.n_chunks >= 4 and job.reduce_nodes_done >= 3
    return {"jid": job.job_id, "n_chunks": job.n_chunks,
            "n_nodes": job.reduce_nodes_done,
            "summary": job.result["summary"]}


def _spawn_job_child(tmp_path, backend: str, rec_type: str, n: int,
                     stall_s: float = 0.4) -> Path:
    """Run one durable job in its own OS process, SIGKILL it once >= n
    records of rec_type are durably framed, and return the jobs dir.
    The stall plan paces appends so the kill window between records is
    wide and machine-speed independent (stalls never change WHAT is
    written, only when)."""
    jobs_dir = Path(tmp_path) / "jobs"
    jobs_dir.mkdir()
    spec = Path(tmp_path) / "spec.json"
    spec.write_text(json.dumps({"jobs_dir": str(jobs_dir),
                                "backend": backend,
                                "transcript": jw.job_transcript()}))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               LMRS_FAULT_PLAN=json.dumps({"faults": [
                   {"site": "journal.append", "every": 1,
                    "action": "stall", "stall_s": stall_s}]}))
    proc = subprocess.Popen(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "_job_worker.py"),
         str(spec)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    wal = None
    try:
        deadline = time.time() + 240  # child compile included (cold cache)
        while time.time() < deadline:
            if proc.poll() is not None:
                raise RuntimeError("job child exited before the kill: "
                                   + proc.stderr.read().decode()[-2000:])
            wal = next(iter(jobs_dir.glob("*.wal")), None)
            if wal is not None:
                recs, _ = jl.replay(wal)
                if sum(1 for r in recs if r.get("type") == rec_type) >= n:
                    break
            time.sleep(0.02)
        else:
            raise TimeoutError(f"never saw {n} {rec_type} record(s)")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    state = jl.rebuild_state(jl.replay(wal)[0])
    assert state["done"] is None, "kill raced past completion — widen stall"
    return jobs_dir


def _resume(jobs_dir, baseline, backend: str):
    """Recover + rerun; assert the durable-job contract (auditor clean on
    the jax arm; token identity asserted by each caller per arm)."""
    eng = jw.build_engine(backend)
    jm = JobManager(eng, jobs_dir, config=jw.job_pipeline_config(backend),
                    start_worker=False)
    assert jm.recover() == 1
    job = jm.get(baseline["jid"])
    assert job is not None and job.recovered
    jm.run_job(job)
    jm.shutdown()
    assert job.status == "done", job.error
    if backend == "jax":
        assert eng._scheduler.audit() == []
        eng.shutdown()
    return job


@pytest.fixture(scope="module")
def mock_killed_mid_map(mock_job_baseline, tmp_path_factory):
    """ONE mock child killed mid-map, >= 2 chunk summaries journaled; the
    plain / torn-tail / duplicate-replay scenarios each resume their own
    COPY, so one subprocess serves three crash-window variants."""
    d = tmp_path_factory.mktemp("job_chaos_kill_mock")
    jobs_dir = _spawn_job_child(d, "mock", "chunk_done", 2, stall_s=0.3)
    state = jl.rebuild_state(jl.replay(next(jobs_dir.glob("*.wal")))[0])
    assert len(state["chunks"]) < mock_job_baseline["n_chunks"], \
        "kill landed after map completed — widen stall"
    return jobs_dir


def test_chaos_job_sigkill_mid_map_token_identical(mock_job_baseline,
                                                   mock_killed_mid_map,
                                                   tmp_path):
    """SIGKILL mid-map: journaled chunk summaries rehydrate instead of
    recomputing and the resumed greedy summary is token-identical."""
    d = tmp_path / "resume"
    shutil.copytree(mock_killed_mid_map, d)
    job = _resume(d, mock_job_baseline, "mock")
    assert 2 <= job.resumed_chunks < mock_job_baseline["n_chunks"]
    assert job.result["num_resumed_chunks"] == job.resumed_chunks
    assert job.result["summary"] == mock_job_baseline["summary"]


def test_chaos_job_sigkill_mid_reduce_token_identical(mock_job_baseline,
                                                      tmp_path):
    """SIGKILL mid-reduce (every chunk + >= 1 reduce node journaled): the
    resumed run answers the journaled nodes from their content-addressed
    keys — it resumes at the exact tree node, not the stage start — and
    the final summary is token-identical."""
    jobs_dir = _spawn_job_child(tmp_path, "mock", "reduce_node_done", 1,
                                stall_s=0.3)
    job = _resume(jobs_dir, mock_job_baseline, "mock")
    assert job.resumed_chunks == mock_job_baseline["n_chunks"]
    assert job.reduce_nodes_reused >= 1
    assert job.result["summary"] == mock_job_baseline["summary"]


def test_chaos_job_torn_tail_resume(mock_job_baseline, mock_killed_mid_map,
                                    tmp_path):
    """The SIGKILL additionally tears the final append (half a frame, no
    newline): replay drops exactly the torn record and the resume still
    lands token-identical."""
    d = tmp_path / "resume"
    shutil.copytree(mock_killed_mid_map, d)
    wal = next(d.glob("*.wal"))
    with open(wal, "ab") as fh:
        fh.write(b'deadbeef {"type":"chunk_done","chunk_in')
    _recs, meta = jl.replay(wal)
    assert meta["torn"] is True
    job = _resume(d, mock_job_baseline, "mock")
    assert job.resumed_chunks >= 2
    assert job.result["summary"] == mock_job_baseline["summary"]


def test_chaos_job_duplicate_replay_resume(mock_job_baseline,
                                           mock_killed_mid_map, tmp_path):
    """Every surviving record appended twice (a crash window re-append):
    rebuild is idempotent, so the duplicates neither double-count resumed
    work nor perturb the token-identical summary."""
    d = tmp_path / "resume"
    shutil.copytree(mock_killed_mid_map, d)
    wal = next(d.glob("*.wal"))
    lines = wal.read_bytes().split(b"\n")[:-1]
    wal.write_bytes(b"\n".join(lines + lines) + b"\n")
    doubled = jl.rebuild_state(jl.replay(wal)[0])
    # byte-identical state vs the un-duplicated journal
    orig = next(mock_killed_mid_map.glob("*.wal"))
    assert (jl.canonical_json(jl.rebuild_state(jl.replay(orig)[0]))
            == jl.canonical_json(doubled))
    job = _resume(d, mock_job_baseline, "mock")
    # duplicates rehydrate exactly once, never double-count
    assert job.resumed_chunks == len(doubled["chunks"])
    assert job.result["summary"] == mock_job_baseline["summary"]


def test_chaos_job_jax_sigkill_mid_map_audited(jax_job_baseline, tmp_path):
    """SIGKILL mid-map on the REAL engine: recovery re-queues, journaled
    chunks rehydrate, the resumed run completes with the page/refcount
    auditor clean.  (Token identity for partial-wave recomputes is the
    mock arm's assertion — content-free random-init logits are knife-edge
    under wave recomposition; see the section comment.)"""
    jobs_dir = _spawn_job_child(tmp_path, "jax", "chunk_done", 2)
    job = _resume(jobs_dir, jax_job_baseline, "jax")
    assert job.resumed_chunks >= 2
    assert job.result["num_resumed_chunks"] == job.resumed_chunks


def test_chaos_job_jax_sigkill_mid_reduce_audited(jax_job_baseline, tmp_path):
    """SIGKILL mid-reduce on the REAL engine (every chunk + >= 1 reduce
    node journaled): the resumed run answers the journaled nodes from
    their content-addressed keys, completes, and the page/refcount
    auditor is clean.  (Identity for the partially recomputed tree is the
    mock arm's assertion — see the section comment.)"""
    jobs_dir = _spawn_job_child(tmp_path, "jax", "reduce_node_done", 1)
    job = _resume(jobs_dir, jax_job_baseline, "jax")
    assert job.resumed_chunks == jax_job_baseline["n_chunks"]
    assert job.reduce_nodes_reused >= 1


def test_chaos_job_jax_sigkill_before_done_token_identical(jax_job_baseline,
                                                           tmp_path):
    """SIGKILL in the last crash window of a job's life: the root reduce
    node is durable but the terminal ``job_done`` record is not.
    Finalization is then PURE journal replay — zero recompute — so strict
    token identity holds even on the knife-edge chaos weights, proving
    the journal alone carries the complete result on the real engine."""
    jobs_dir = _spawn_job_child(tmp_path, "jax", "reduce_node_done",
                                jax_job_baseline["n_nodes"])
    job = _resume(jobs_dir, jax_job_baseline, "jax")
    assert job.resumed_chunks == jax_job_baseline["n_chunks"]
    assert job.reduce_nodes_reused == jax_job_baseline["n_nodes"]
    assert job.result["summary"] == jax_job_baseline["summary"]


def test_chaos_spill_prefetch_faults_token_identical(jax_engine):
    """Host-RAM spill tier under fire (ISSUE 12): with real page pressure
    and forced evictions, ``prefix.spill`` faults degrade captures to
    evict-means-gone and ``prefix.prefetch`` faults truncate matches back
    to re-prefill — greedy outputs stay token-identical to fault-free
    runs and the auditor (including the host-pool accounting cross-check)
    is clean after every wave."""
    sched = jax_engine._scheduler
    pre = "Shared chaos preamble: keep every fact, name, and number. "

    def reqs():
        return [GenerationRequest(
            prompt=pre + f"chunk {i}: the team discussed item {i}.",
            request_id=900 + i, temperature=0.0, max_new_tokens=8,
            cache_prefix=len(pre)) for i in range(5)]

    baseline = [r.text for r in jax_engine.generate_batch(reqs())]
    assert sched.audit() == []
    pc = sched._prefix_cache
    assert pc is not None and pc.pool is not None
    plan = [{"site": "prefix.spill", "p": 0.5},
            {"site": "prefix.prefetch", "p": 0.5}]
    with faults.injected(FaultPlan(seed=29, faults=plan)):
        pc.evict(10_000)  # spill wave: some captures fault -> hard drop
        assert sched.audit() == []
        mid = [r.text for r in jax_engine.generate_batch(reqs())]
        assert sched.audit() == []
        pc.evict(10_000)
        last = [r.text for r in jax_engine.generate_batch(reqs())]
    assert sched.audit() == []
    assert mid == baseline
    assert last == baseline


# ---------------------------------------------- kill-a-host KV-fabric chaos
# ISSUE 20 acceptance: SIGKILL a backend mid-live-session and the session
# resumes on a sibling, with >= 50% of its re-served prefill tokens coming
# off the KV fabric (migrated page sets) instead of cold re-prefill.  Two
# arms, two halves of the contract (the durable-job split above):
#
# * MOCK, two OS processes sharing one --live-dir: the router drains the
#   session's owner (migrating its warm preambles over the /v1/kv wire),
#   the owner is SIGKILL'd, and follow-up session traffic resumes on the
#   sibling via on-demand WAL rehydration — final summary token-identical
#   to an uninterrupted single-backend run, resume preamble queries served
#   from the migrated entries.
# * JAX, in-process: kv_export on one engine -> kv_import on a fresh
#   engine -> re-run; token identity, the >= 50% fabric-token ratio from
#   the scheduler's own prefill/reuse counters, and scheduler + cost-
#   ledger audits clean on BOTH engines.

from tests.conftest import free_port, make_segments  # noqa: E402


def _fab_call(port: int, method: str, path: str, body=None,
              timeout: float = 120.0):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    conn.request(method, path,
                 body=None if body is None else json.dumps(body),
                 headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    data = json.loads(r.read())
    conn.close()
    return r.status, data


def _spawn_live_worker(port: int, live_dir: str) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "lmrs_tpu.serving.cli",
         "--backend", "mock", "--port", str(port),
         "--live-dir", live_dir, "-q"],
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _wait_live(port: int, proc, deadline_s: float = 60.0) -> None:
    t0 = time.time()
    while time.time() - t0 < deadline_s:
        if proc.poll() is not None:
            raise RuntimeError("live worker died rc=%s: %s" % (
                proc.returncode, proc.stderr.read().decode()[-2000:]))
        try:
            st, _ = _fab_call(port, "GET", "/healthz", timeout=2.0)
            if st == 200:
                return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"worker :{port} never became healthy")


def _fab_segments() -> tuple[list[dict], list[dict]]:
    segs = make_segments(80, seed=13)
    return segs[:50], segs[50:]


@pytest.fixture(scope="module")
def fabric_baseline(tmp_path_factory):
    """Uninterrupted single-backend run of the exact session sequence the
    chaos arm replays: the token-identity reference."""
    d = tmp_path_factory.mktemp("fabric_ref")
    port = free_port()
    proc = _spawn_live_worker(port, str(d / "live"))
    part_a, part_b = _fab_segments()
    try:
        _wait_live(port, proc)
        st, doc = _fab_call(port, "POST", "/v1/sessions",
                            {"session_id": "fab"})
        assert st == 200, doc
        st, doc = _fab_call(port, "POST", "/v1/sessions/fab/segments",
                            {"segments": part_a, "refresh": True})
        assert st == 200, doc
        sum_a = doc["refresh"]["summary"]
        st, doc = _fab_call(port, "POST", "/v1/sessions/fab/segments",
                            {"segments": part_b, "refresh": True})
        assert st == 200, doc
        sum_b = doc["refresh"]["summary"]
        assert sum_a and sum_b
    finally:
        proc.terminate()
        proc.wait(timeout=10)
    return {"sum_a": sum_a, "sum_b": sum_b}


def test_chaos_kill_a_host_session_resumes_on_fabric(fabric_baseline,
                                                     tmp_path):
    """The kill-a-host gate: drain migrates the owner's warm KV, SIGKILL
    takes the owner down mid-session, and the sibling serves the rest of
    the session token-identically with its resume preamble queries hitting
    the migrated page sets."""
    from lmrs_tpu.serving.router import RouterEngine

    live_dir = str(tmp_path / "live")  # SHARED: journals replay anywhere
    ports = [free_port(), free_port()]
    procs = [_spawn_live_worker(p, live_dir) for p in ports]
    part_a, part_b = _fab_segments()
    router = None
    try:
        for p, pr in zip(ports, procs):
            _wait_live(p, pr)
        router = RouterEngine([f"127.0.0.1:{p}" for p in ports])
        st, doc = router.session_request(
            "POST", "/v1/sessions", {"session_id": "fab"})
        assert st == 200, doc
        st, doc = router.session_request(
            "POST", "/v1/sessions/fab/segments",
            {"segments": part_a, "refresh": True})
        assert st == 200, doc
        assert doc["refresh"]["summary"] == fabric_baseline["sum_a"]
        with router._job_lock:
            owner = router._job_hosts["fab"]
        owner_port = int(owner.rsplit(":", 1)[1])
        sib_port = next(p for p in ports if p != owner_port)

        # drain: purges sticky state, migrates warm KV, re-pins the session
        assert router.drain_host(owner)
        deadline = time.time() + 20.0
        while (router.migrations_pending(owner)
               and time.time() < deadline):
            time.sleep(0.1)
        assert not router.migrations_pending(owner)
        assert router._kv_moves >= 1, "no page set travelled the fabric"

        # SIGKILL mid-live-session (the session is open with more
        # segments to come), then force-remove the dead pod
        os.kill(procs[ports.index(owner_port)].pid, signal.SIGKILL)
        procs[ports.index(owner_port)].wait(timeout=10)
        assert router.remove_host(owner, force=True)

        # resume: the sibling rehydrates the journal on demand and serves
        # the rest of the session token-identical to the uninterrupted run
        st, doc = router.session_request(
            "POST", "/v1/sessions/fab/segments",
            {"segments": part_b, "refresh": True})
        assert st == 200, doc
        assert doc["refresh"]["summary"] == fabric_baseline["sum_b"]
        st, doc = router.session_request("GET", "/v1/sessions/fab", None)
        assert st == 200 and doc["recovered"] is True

        # >= 50% of the re-served prefill tokens came off the fabric: the
        # sibling was idle until the resume, so its prefix entries could
        # only have arrived via kv_import — every resume preamble hit is
        # fabric-served.  Queries measure preamble re-serves; reused >=
        # imported means the migrated page set was re-served in full.
        st, m = _fab_call(sib_port, "GET", "/metrics")
        assert st == 200
        mig = m["engine"]["kv_migrate"]
        assert mig["imports"] >= 1 and mig["tokens_imported"] > 0
        pc = m["engine"]["prefix_cache"]
        assert pc["queries"] >= 1
        assert pc["hits"] / pc["queries"] >= 0.5, pc
        assert pc["tokens_reused"] >= mig["tokens_imported"], pc
    finally:
        if router is not None:
            router.shutdown()
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()
        for pr in procs:
            try:
                pr.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pr.kill()
                pr.wait(timeout=10)


def test_chaos_kv_fabric_jax_migration_audited():
    """The in-process jax arm: export a warm preamble's page set from one
    engine, import into a FRESH engine, re-run the same greedy workload —
    token identity, >= 50% of the importing engine's prefill tokens served
    from the fabric, and scheduler + cost-ledger audits clean on both."""
    cfg = EngineConfig(backend="jax", scheduler="continuous",
                       max_tokens=16, max_batch_slots=2, seed=0,
                       decode_block=4, page_size=16, num_pages=32)
    pre = ("Fabric preamble, shared by every chunk of this session: keep "
           "every fact, decision, name, and number exactly as stated. "
           + " ".join(_WORDS) + ". ")

    def reqs():
        return [GenerationRequest(
            prompt=pre + f"chunk {i}: item {i} closed.", request_id=i,
            temperature=0.0, max_new_tokens=6, cache_prefix=len(pre))
            for i in range(2)]

    e1 = JaxEngine(cfg, chaos_model())
    e2 = JaxEngine(cfg, chaos_model())
    try:
        base = e1.generate_batch(reqs())
        assert all(r.error is None for r in base)
        from lmrs_tpu.engine.api import preamble_key
        key = preamble_key(None, pre + "chunk 0: item 0 closed.", len(pre))
        payload = e1.kv_export(key)
        assert payload is not None and payload["tokens"] > 0
        moved = e2.kv_import(payload)
        assert moved == payload["tokens"]
        redo = e2.generate_batch(reqs())
        assert [r.text for r in redo] == [r.text for r in base]
        m = e2._scheduler.metrics
        reused, fresh = m["prefix_tokens_reused"], m["prefill_tokens"]
        assert reused > 0
        assert reused / (reused + fresh) >= 0.5, (reused, fresh)
        assert e1._scheduler.audit() == []
        assert e2._scheduler.audit() == []
        assert e1._scheduler._cost.audit() == []
        assert e2._scheduler._cost.audit() == []
    finally:
        e1.shutdown()
        e2.shutdown()
