"""Real-tokenizer path (VERDICT r2 item 6): the SentencePiece/HF adapters
must not be dead code gated on assets this zero-egress image lacks.

``transformers`` + ``tokenizers`` ARE in the image, so a real BPE tokenizer
is TRAINED in-tree at test time on the synthetic corpus, saved in HF format,
and driven through the full stack: ``get_tokenizer`` resolution → chunk
budgeting → CLI config → the continuous-batching engine (encode and decode
through a non-byte vocabulary).  ``SentencePieceTokenizer`` keeps its gated
import (no ``sentencepiece`` wheel here) — its adapter shape is identical
and the resolution branch is covered below.

Reference counterpart: the vendor tokenizer behind llm_executor.py:250-326
(tiktoken cl100k_base as count authority, big_chunkeroosky.py:27).
"""

from __future__ import annotations

import json

import pytest

pytest.importorskip("tokenizers")
pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def hf_tok_dir(tmp_path_factory):
    """Train a tiny BPE tokenizer on the synthetic transcript corpus and
    save it in HF (PreTrainedTokenizerFast) format."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers

    from tests.conftest import make_segments

    corpus = [s["text"] for s in make_segments(400, seed=7)]
    tok = Tokenizer(models.BPE(unk_token="<unk>"))
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = trainers.BpeTrainer(
        vocab_size=400,
        special_tokens=["<pad>", "<s>", "</s>", "<unk>"])
    tok.train_from_iterator(corpus, trainer)

    d = tmp_path_factory.mktemp("hf_tok")
    tok.save(str(d / "tokenizer.json"))
    (d / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "bos_token": "<s>", "eos_token": "</s>",
        "pad_token": "<pad>", "unk_token": "<unk>",
    }))
    return str(d)


def test_get_tokenizer_resolves_hf_dir(hf_tok_dir):
    from lmrs_tpu.data.tokenizer import HFTokenizer, get_tokenizer

    tok = get_tokenizer(hf_tok_dir)
    assert isinstance(tok, HFTokenizer)
    assert 0 < tok.vocab_size <= 512
    ids = tok.encode("the project timeline depends on shipping")
    assert ids and all(0 <= i < tok.vocab_size for i in ids)
    assert tok.count("the project timeline") == len(tok.encode("the project timeline"))
    # decode inverts encode up to whitespace normalization
    assert "project" in tok.decode(ids)


def test_get_tokenizer_sentencepiece_branch_is_gated():
    """*.model resolves to the SentencePiece adapter; without the wheel the
    gated import raises ImportError (not a silent fallback)."""
    from lmrs_tpu.data.tokenizer import get_tokenizer

    try:
        import sentencepiece  # noqa: F401
        pytest.skip("sentencepiece present: gate untestable")
    except ImportError:
        pass
    with pytest.raises((ImportError, OSError)):
        get_tokenizer("/nonexistent/vocab.model")


def test_chunk_budgets_in_hf_tokens(hf_tok_dir):
    """Chunk budgets measured by the REAL tokenizer (SURVEY §7.4 item 4),
    not the 4-chars/token approximation."""
    from lmrs_tpu.data.chunker import TranscriptChunker
    from lmrs_tpu.data.tokenizer import get_tokenizer

    from tests.conftest import make_segments

    tok = get_tokenizer(hf_tok_dir)
    chunker = TranscriptChunker(max_tokens_per_chunk=120, overlap_tokens=0,
                                context_tokens=20, tokenizer=tok)
    chunks = chunker.chunk_transcript(make_segments(120, seed=3))
    assert len(chunks) > 1
    for c in chunks:
        # same contract as test_chunker.test_budget_respected: packed
        # segment text measured in the REAL tokenizer fits the budget
        packed = sum(tok.count(s["text"]) for s in c.segments)
        assert packed <= chunker.effective_max_tokens


def test_engine_generates_through_hf_tokenizer(hf_tok_dir):
    """CLI config → engine: --tokenizer names the serving tokenizer, the
    engine encodes prompts and decodes completions through the trained BPE
    vocabulary (vocab_size must cover it)."""
    from lmrs_tpu.config import EngineConfig, ModelConfig
    from lmrs_tpu.engine.api import GenerationRequest
    from lmrs_tpu.engine.jax_engine import JaxEngine

    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                     dtype="float32")
    eng = JaxEngine(
        EngineConfig(backend="jax", scheduler="continuous", max_tokens=16,
                     max_batch_slots=2, seed=0, decode_block=8,
                     tokenizer=hf_tok_dir),
        mc)
    assert type(eng.tokenizer).__name__ == "HFTokenizer"
    out = eng.generate_batch([
        GenerationRequest(prompt="the project timeline depends on shipping",
                          request_id=0, temperature=0.8, max_new_tokens=16)])
    assert out[0].error is None
    assert out[0].prompt_tokens > 0
    # the completion decodes through the BPE vocab: pieces are corpus words/
    # subwords, not raw bytes
    assert isinstance(out[0].text, str)
    eng.shutdown()


def test_cli_tokenizer_flag_flows_to_engine_and_chunker(hf_tok_dir):
    """--tokenizer <hf dir> must reach BOTH the chunker (count authority)
    and the jax engine (serving vocabulary) through config_from_args."""
    import argparse

    from lmrs_tpu.cli import build_parser, config_from_args

    args = build_parser().parse_args([
        "--input", "unused.json", "--backend", "jax",
        "--tokenizer", hf_tok_dir])
    assert isinstance(args, argparse.Namespace)
    cfg = config_from_args(args)
    assert cfg.chunk.tokenizer == hf_tok_dir
    assert cfg.engine.tokenizer == hf_tok_dir


def test_pipeline_end_to_end_with_hf_tokenizer(hf_tok_dir):
    """Full map-reduce through the jax engine with the HF tokenizer as the
    single token authority: CLI-shaped config → chunker budgets → engine
    encode/decode → reduce."""
    from lmrs_tpu.config import (
        ChunkConfig, EngineConfig, ModelConfig, PipelineConfig, ReduceConfig,
    )
    from lmrs_tpu.pipeline import TranscriptSummarizer

    from tests.conftest import make_segments

    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                     dtype="float32")
    cfg = PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=200, overlap_tokens=0,
                          context_tokens=30, tokenizer=hf_tok_dir),
        engine=EngineConfig(backend="jax", scheduler="continuous",
                            max_tokens=24, max_batch_slots=2, seed=0,
                            decode_block=8, tokenizer=hf_tok_dir),
        model=mc,
        reduce=ReduceConfig(max_tokens_per_batch=400),
    )
    s = TranscriptSummarizer(cfg)
    stats = s.summarize({"segments": make_segments(60, seed=11)})
    assert stats["num_chunks"] >= 1
    assert stats["failed_requests"] == 0
    assert isinstance(stats["summary"], str)
