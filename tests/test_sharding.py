"""Multi-chip sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4:
emulate TP/DP without TPUs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import MeshConfig, ModelConfig
from lmrs_tpu.models.transformer import forward, init_params
from lmrs_tpu.parallel.mesh import build_mesh
from lmrs_tpu.parallel.sharding import param_shardings, shard_params

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _require_donated_sharded_steps():
    """Skip-with-reason when donated sharded train updates are broken on
    this build (the pinned CPU jaxlib fails donation aliasing under
    dp×tp meshes with ``INTERNAL: Expected aliased input ...``) — a
    detected environment capability, not a repo regression.  The probe
    (utils/jax_compat.sharded_donation_error) runs the repo's own micro
    train step once per process and memoizes."""
    from lmrs_tpu.utils.jax_compat import sharded_donation_error

    err = sharded_donation_error()
    if err:
        pytest.skip("donated sharded train steps broken on this jaxlib "
                    f"build (environmental): {err[:160]}")


def cfg8():
    return ModelConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
                       hidden_dim=64, max_seq_len=128, dtype="float32")


def test_build_mesh_axes():
    mesh = build_mesh(MeshConfig(dp=2, tp=2, sp=2, pp=1))
    assert mesh.shape == {"dp": 2, "tp": 2, "sp": 2, "ep": 1, "pp": 1}


def test_mesh_too_big_raises():
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(dp=16, tp=2))


def test_tp_sharded_forward_matches_single_device():
    """TP=2 sharded forward must be numerically identical (up to f32 noise)
    to the unsharded forward — XLA inserts the collectives."""
    cfg = cfg8()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64)
    pos = jnp.broadcast_to(jnp.arange(16)[None], (2, 16))

    ref_logits, _ = forward(params, cfg, tokens, pos)

    mesh = build_mesh(MeshConfig(dp=2, tp=2, sp=1, pp=1))
    sharded = shard_params(params, mesh, cfg.tie_embeddings)

    @jax.jit
    def run(p, t, pos):
        logits, _ = forward(p, cfg, t, pos)
        return logits

    out = run(sharded, tokens, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=5e-4, atol=5e-5)


def test_param_sharding_layout():
    """Head/vocab/ffn axes actually land on the tp mesh axis."""
    cfg = cfg8()
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = build_mesh(MeshConfig(dp=1, tp=2, sp=1, pp=1), jax.devices()[:2])
    sharded = shard_params(params, mesh, cfg.tie_embeddings)
    wq = sharded["layers"]["attn"]["wq"]
    # wq [L, D, H, hd] sharded on H over tp=2: per-device shard has H/2
    shard_shape = wq.sharding.shard_shape(wq.shape)
    assert shard_shape[2] == cfg.n_heads // 2
    emb = sharded["embed"]["weight"]
    assert emb.sharding.shard_shape(emb.shape)[0] == cfg.vocab_size // 2


def test_training_step_on_mesh():
    """Full sharded train step (the dryrun_multichip path) runs and reduces
    loss over a few steps on memorizable data."""
    _require_donated_sharded_steps()
    import optax

    from lmrs_tpu.training.train import make_train_step

    cfg = cfg8()
    mesh = build_mesh(MeshConfig(dp=2, tp=2, sp=2, pp=1))
    params = shard_params(init_params(cfg, jax.random.PRNGKey(0)), mesh,
                          cfg.tie_embeddings)
    opt = optax.adam(5e-3)
    opt_state = opt.init(params)
    step = make_train_step(cfg, opt, mesh, seq_sharded=True)
    tokens = jnp.asarray(
        np.tile(np.arange(32, dtype=np.int32)[None], (4, 2)).reshape(4, 64) % 64
    )
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_dryrun_multichip_entrypoint():
    _require_donated_sharded_steps()
    import importlib.util, pathlib

    spec = importlib.util.spec_from_file_location(
        "graft_entry", pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.dryrun_multichip(8)


def test_seq_sharded_ring_loss_matches_unsharded():
    """sp>1 training loss (ring attention path) must equal the unsharded
    causal-LM loss to f32 tolerance."""
    import optax

    from lmrs_tpu.training.train import causal_lm_loss, make_train_step

    cfg = cfg8()
    params = init_params(cfg, jax.random.PRNGKey(3))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 64), 0, 64)
    want = float(causal_lm_loss(params, cfg, tokens))

    mesh = build_mesh(MeshConfig(dp=2, tp=1, sp=4, pp=1))
    sharded = shard_params(params, mesh, cfg.tie_embeddings)
    opt = optax.sgd(0.0)  # zero LR: step returns the pristine loss
    step = make_train_step(cfg, opt, mesh, seq_sharded=True)
    _, _, loss = step(sharded, opt.init(sharded), tokens)
    np.testing.assert_allclose(float(loss), want, rtol=1e-4)


def test_remat_grads_match_non_remat():
    """jax.checkpoint per layer must not change loss or grads (only the
    backward-pass memory/FLOP schedule)."""
    from lmrs_tpu.training.train import causal_lm_loss

    cfg = cfg8()
    params = init_params(cfg, jax.random.PRNGKey(5))
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 32), 0, 64)
    l_ref, g_ref = jax.value_and_grad(causal_lm_loss)(params, cfg, tokens)
    l_rm, g_rm = jax.value_and_grad(causal_lm_loss)(params, cfg, tokens,
                                                    remat=True)
    np.testing.assert_allclose(float(l_rm), float(l_ref), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_rm)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-6)


def test_remat_train_step_on_mesh():
    _require_donated_sharded_steps()
    import optax

    from lmrs_tpu.training.train import make_train_step

    cfg = cfg8()
    mesh = build_mesh(MeshConfig(dp=2, tp=2, sp=1, pp=1), jax.devices()[:4])
    params = shard_params(init_params(cfg, jax.random.PRNGKey(7)), mesh,
                          cfg.tie_embeddings)
    opt = optax.adam(1e-3)
    step = make_train_step(cfg, opt, mesh, remat=True)
    tokens = jnp.asarray(
        np.random.default_rng(7).integers(0, 64, (4, 32), dtype=np.int32))
    _, _, loss = step(params, opt.init(params), tokens)
    assert np.isfinite(float(loss))
