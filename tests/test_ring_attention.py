"""Ring attention (context parallelism) vs the dense XLA reference.

Runs on the virtual 8-device CPU mesh (conftest).  Numerics must match dense
causal attention to float32 tolerance — the ring computes the same online
softmax, just with K/V blocks arriving over ppermute hops.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lmrs_tpu.config import MeshConfig
from lmrs_tpu.ops.attention import attention
from lmrs_tpu.parallel.mesh import build_mesh
from lmrs_tpu.parallel.ring_attention import ring_attention_sharded


def _rand_qkv(key, b, s, h, kh, hd):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, hd), jnp.float32)
    k = jax.random.normal(kk, (b, s, kh, hd), jnp.float32)
    v = jax.random.normal(kv, (b, s, kh, hd), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
    return q, k, v, pos


@pytest.mark.parametrize("mesh_cfg,h,kh", [
    (MeshConfig(dp=2, tp=1, sp=4), 4, 4),   # MHA, dp x sp
    (MeshConfig(dp=2, tp=1, sp=4), 4, 2),   # GQA
    (MeshConfig(dp=1, tp=2, sp=4), 4, 2),   # composed with tensor parallelism
    (MeshConfig(dp=1, tp=1, sp=8), 8, 8),   # full ring
])
def test_ring_matches_dense(mesh_cfg, h, kh):
    mesh = build_mesh(mesh_cfg, jax.devices()[: mesh_cfg.n_devices])
    b, s, hd = 2, 64, 16
    q, k, v, pos = _rand_qkv(jax.random.PRNGKey(0), b, s, h, kh, hd)

    want = attention(q, k, v, pos)
    got = ring_attention_sharded(q, k, v, pos, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_softcap():
    cfg = MeshConfig(dp=1, tp=1, sp=4)
    mesh = build_mesh(cfg, jax.devices()[:4])
    q, k, v, pos = _rand_qkv(jax.random.PRNGKey(1), 1, 32, 4, 4, 8)
    want = attention(q, k, v, pos, logit_softcap=30.0)
    got = ring_attention_sharded(q, k, v, pos, mesh, logit_softcap=30.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_under_jit():
    """Ring attention inside jit (how the model actually calls it)."""
    cfg = MeshConfig(dp=2, tp=1, sp=4)
    mesh = build_mesh(cfg, jax.devices()[:8])
    q, k, v, pos = _rand_qkv(jax.random.PRNGKey(2), 2, 32, 4, 2, 8)

    fn = jax.jit(lambda q, k, v, p: ring_attention_sharded(q, k, v, p, mesh))
    got = fn(q, k, v, pos)
    want = attention(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_dense():
    """Backward through the ring (ppermute transpose + online-softmax remat)
    must match dense-attention gradients — a zero-LR loss check alone would
    miss a broken backward."""
    from lmrs_tpu.config import ModelConfig
    from lmrs_tpu.models.transformer import init_params
    from lmrs_tpu.parallel.sharding import shard_params
    from lmrs_tpu.training.train import causal_lm_loss

    cfg = ModelConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=64, max_seq_len=128,
                      dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(5))
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 32), 0, 64)
    want = jax.grad(causal_lm_loss)(params, cfg, tokens)

    mesh = build_mesh(MeshConfig(dp=2, tp=1, sp=4), jax.devices()[:8])
    sharded = shard_params(params, mesh, cfg.tie_embeddings)

    def ring_fn(q, k, v, pos):
        return ring_attention_sharded(q, k, v, pos, mesh)

    got = jax.jit(
        lambda p, t: jax.grad(causal_lm_loss)(p, cfg, t, attn_fn=ring_fn)
    )(sharded, tokens)
    flat_w, _ = jax.tree.flatten(want)
    flat_g, _ = jax.tree.flatten(got)
    for w, g in zip(flat_w, flat_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-4, atol=1e-5)


def test_ring_kv_pos_masks_padded_keys():
    """Serving ring prefill masks pad keys positionally (kv_pos pushed past
    every query): valid rows must match XLA attention with kv_length."""
    from lmrs_tpu.ops.attention import attention

    b, s, h, kh, hd = 2, 64, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(ks[0], (b, s, h, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kh, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kh, hd), jnp.float32)
    lengths = jnp.asarray([s, s // 4], jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    kv_pos = jnp.where(jnp.arange(s)[None] < lengths[:, None], pos,
                       jnp.int32(1 << 30))

    mesh = build_mesh(MeshConfig(dp=1, tp=1, sp=4), jax.devices()[:4])
    got = ring_attention_sharded(q, k, v, pos, mesh, kv_pos=kv_pos)
    want = attention(q, k, v, pos, lengths)
    for i, n in enumerate([s, s // 4]):
        np.testing.assert_allclose(np.asarray(got[i, :n]),
                                   np.asarray(want[i, :n]),
                                   rtol=2e-5, atol=2e-5)
