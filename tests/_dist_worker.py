"""Worker process for tests/test_distributed.py.

Runs ONE data-parallel train step over a GLOBAL mesh that spans two
OS processes (2 local CPU devices each — the multi-host DCN topology in
miniature: gradient psums cross the process boundary over the gloo
backend exactly where a pod crosses DCN).  Usage:

    python tests/_dist_worker.py <process_id> <coordinator> <out_file>

Module top is side-effect free: the test process imports ``make_cfg`` /
``make_global_tokens`` (one shared workload definition — no copy-paste
drift between the worker and the single-process parity check), so env
setup happens only under ``__main__``.
"""

from __future__ import annotations

import sys


def make_cfg():
    from lmrs_tpu.config import ModelConfig

    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=64,
                       dtype="float32")


def make_global_tokens():
    """Deterministic global batch [4, 64] (one row per dp device)."""
    import numpy as np

    return np.random.default_rng(42).integers(3, 258, (4, 64)).astype(np.int32)


def main() -> None:
    import os
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    import jax

    jax.config.update("jax_platforms", "cpu")

    pid, coordinator, out_file = int(sys.argv[1]), sys.argv[2], sys.argv[3]

    from lmrs_tpu.parallel.mesh import build_mesh, initialize_distributed

    initialize_distributed(coordinator=coordinator, num_processes=2,
                           process_id=pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 4, "2 procs x 2 local devices"

    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from lmrs_tpu.config import MeshConfig
    from lmrs_tpu.models.transformer import init_params
    from lmrs_tpu.training.train import make_train_step

    cfg = make_cfg()
    mesh = build_mesh(MeshConfig(dp=4))
    params = init_params(cfg, jax.random.PRNGKey(0))  # same seed: replicated
    optimizer = optax.sgd(1e-2)
    opt_state = optimizer.init(params)
    step = make_train_step(cfg, optimizer, mesh)

    # this process owns rows [2*pid, 2*pid+2) — the dp shard that lives on
    # its local devices
    global_tokens = make_global_tokens()
    local_rows = global_tokens[2 * pid: 2 * pid + 2]
    sharding = NamedSharding(mesh, P("dp", None))
    tokens = jax.make_array_from_process_local_data(sharding, local_rows)

    params, opt_state, loss = step(params, opt_state, tokens)
    # loss is a replicated scalar: every process must see the same value
    loss_val = float(loss)

    # one more step to prove updated (cross-process-psummed) params stay
    # consistent and usable
    params, opt_state, loss2 = step(params, opt_state, tokens)

    with open(out_file, "w") as f:
        f.write(f"{loss_val:.8f} {float(loss2):.8f} "
                f"{jax.process_index()} {jax.process_count()}\n")
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
