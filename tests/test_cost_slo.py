"""Request-cost ledger + fleet SLO engine (ISSUE 15).

The tier-1 ``cost-slo`` gate: ledger conservation must hold as a
scheduler-audit invariant under mixed/spec/prefix-cache and chaos arms,
greedy outputs must be byte-identical with ``LMRS_COST_LEDGER`` on vs
off, the tenant label must propagate router → backend → journal
recovery, the SLO state machine must transition (and flap-damp)
deterministically, SLO-aware routing must shift traffic off a degraded
host without changing outputs, and fleet ``/v1/usage`` rollups must sum
exactly.
"""

from __future__ import annotations

import http.client
import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest, GenerationResult
from lmrs_tpu.engine.mock import MockEngine
from lmrs_tpu.obs.ledger import CostLedger, merge_usage
from lmrs_tpu.obs.slo import SLOEngine, SLOSpec

REPO = Path(__file__).resolve().parent.parent


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                       dtype="float32")


def _cfg(**kw) -> EngineConfig:
    base = dict(backend="jax", scheduler="continuous", max_tokens=16,
                max_batch_slots=2, seed=0, decode_block=3,
                prefill_chunk=64, retry_delay=0.0)
    base.update(kw)
    return EngineConfig(**base)


def _reqs(n: int = 4) -> list[GenerationRequest]:
    pre = "shared ledger preamble alpha beta "
    return [GenerationRequest(
        prompt=(pre if i % 2 else "") + f"request {i} "
        + "lorem ipsum dolor sit amet " * (1 + 4 * (i % 2)),
        request_id=i, temperature=0.0, max_new_tokens=10 + i,
        tenant=f"t{i % 2}") for i in range(n)]


# ------------------------------------------------------------ ledger unit


def test_ledger_apportionment_conserves_exactly():
    led = CostLedger(enabled=True)
    reqs = [GenerationRequest(prompt="x", request_id=i, tenant="a")
            for i in range(3)]
    # odd wall + odd weights: remainder correction must keep per-dispatch
    # sums exact
    led.note_step(0.123456789,
                  decode_rows=[(reqs[0], 3, 4), (reqs[1], 7, 2)],
                  prefill_rows=[(reqs[2], 11, 5.0)],
                  decode_cost_s=0.3, prefill_cost_s=0.7)
    led.note_step(0.001, decode_rows=[(reqs[0], 0, 1), (reqs[1], 0, 1)])
    assert led.audit() == []
    for r in reqs:
        led.finish(r, GenerationResult(request_id=r.request_id,
                                       completion_tokens=2,
                                       prompt_tokens=5))
    assert led.audit() == []
    doc = led.usage_report()
    assert doc["tenants"]["a"]["requests"] == 3
    assert abs(doc["totals"]["device_seconds"]
               - doc["tenants"]["a"]["device_seconds"]) < 1e-12


def test_kv_page_seconds_bill_the_full_dispatch_wall():
    """Pages are resident for the whole kernel launch: a fused mixed
    step whose roofline split hands most of the wall to prefill must
    still bill decode rows' pages x the FULL dispatch wall (the
    module-doc / metrics-catalog definition)."""
    led = CostLedger(enabled=True)
    r0 = GenerationRequest(prompt="x", request_id=0, tenant="a")
    rp = GenerationRequest(prompt="y", request_id=1, tenant="a")
    led.note_step(0.1, decode_rows=[(r0, 1, 10)],
                  prefill_rows=[(rp, 64, 8.0)],
                  decode_cost_s=0.2, prefill_cost_s=0.8)
    assert led.audit() == []
    u = led.finish(r0, GenerationResult(request_id=0, completion_tokens=1,
                                        prompt_tokens=1))
    assert abs(u["kv_page_seconds"] - 10 * 0.1) < 1e-9
    assert u["decode_device_seconds"] < 0.1  # phase split still applies


def test_tenant_cardinality_cap_folds_into_overflow(monkeypatch):
    """Past LMRS_COST_TENANTS_MAX distinct labels the rollups fold into
    the 'other' bucket — bounded memory under job/session-minted
    tenants, with conservation (and the tenants->totals sum) intact."""
    monkeypatch.setenv("LMRS_COST_TENANTS_MAX", "2")
    led = CostLedger(enabled=True)
    for i, tenant in enumerate(("a", "b", "c", "d")):
        r = GenerationRequest(prompt="x", request_id=i, tenant=tenant)
        led.note_step(0.25, decode_rows=[(r, 2, 1)])
        led.finish(r, GenerationResult(request_id=i, completion_tokens=2,
                                       prompt_tokens=1))
    assert led.audit() == []
    doc = led.usage_report()
    assert set(doc["tenants"]) == {"a", "b", "other"}
    assert doc["tenants"]["other"]["requests"] == 2
    assert doc["totals"]["requests"] == 4
    assert abs(doc["totals"]["device_seconds"] - 1.0) < 1e-9


def test_ledger_disabled_is_inert():
    led = CostLedger(enabled=False)
    r = GenerationRequest(prompt="x", request_id=1)
    led.note_step(1.0, decode_rows=[(r, 5, 1)])
    led.note_queue_wait(r, 1.0)
    assert led.finish(r, GenerationResult(request_id=1)) is None
    assert led.audit() == []
    assert led.usage_report()["enabled"] is False


def test_merge_usage_is_the_one_sum_rule():
    a, b = {}, {}
    u1 = {"prefill_device_seconds": 0.5, "decode_device_seconds": 1.5,
          "prompt_tokens": 10, "goodput_tokens": 4}
    u2 = {"prefill_device_seconds": 0.25, "decode_device_seconds": 0.25,
          "prompt_tokens": 3, "wasted_tokens": 2}
    merge_usage(a, u1)
    merge_usage(a, u2)
    merge_usage(b, merge_usage(dict(u1), u2))
    assert a["device_seconds"] == 2.5
    assert a["prompt_tokens"] == 13 and a["requests"] == 2


# --------------------------------------------------------- scheduler arms


@pytest.mark.parametrize("arm", ["plain", "mixed", "spec", "no_prefix"])
def test_ledger_conservation_scheduler_arms(arm):
    """Conservation gated in scheduler.audit() across the dispatch-path
    matrix: plain alternating, mixed fused steps, speculative blocks,
    prefix cache off.  Every arm must also actually bill someone."""
    from lmrs_tpu.engine.jax_engine import JaxEngine

    kw = dict(mixed_batch=arm == "mixed",
              prefix_cache=arm != "no_prefix",
              speculate_k=3 if arm == "spec" else 0)
    eng = JaxEngine(_cfg(**kw), tiny_model())
    out = eng.generate_batch(_reqs())
    sched = eng._scheduler
    assert sched.audit() == []
    assert all(r.error is None for r in out)
    assert all(r.usage is not None for r in out)
    doc = sched.usage_report()
    assert doc["tenants"]["t0"]["requests"] == 2
    assert doc["totals"]["device_seconds"] > 0
    # no orphaned entries: every finished request left the live table —
    # a dispatch note landing AFTER its row's finish would re-create the
    # entry and leak one per completed request
    assert doc["live_requests"] == 0
    # prompt/generated token attribution is exact per result
    for r in out:
        assert r.usage["prompt_tokens"] == r.prompt_tokens
        assert r.usage["generated_tokens"] == r.completion_tokens
    # a second batch keeps conserving (rollup + live entry interplay)
    eng.generate_batch(_reqs())
    assert sched.audit() == []
    eng.shutdown()


def test_ledger_conservation_under_chaos():
    """Faults firing mid-run (OutOfPages + scheduler.step) must leave the
    conservation invariant intact — recovery may drop work, never bill
    it twice."""
    from lmrs_tpu.engine.executor import MapExecutor
    from lmrs_tpu.engine.jax_engine import JaxEngine
    from lmrs_tpu.testing import faults
    from lmrs_tpu.testing.faults import FaultPlan

    eng = JaxEngine(_cfg(mixed_batch=True), tiny_model())
    ex = MapExecutor(eng, EngineConfig(retry_attempts=3, retry_delay=0.0))
    with faults.injected(FaultPlan(seed=91, faults=[
            {"site": "kv_cache.allocate", "p": 0.2, "max_fires": 3},
            {"site": "scheduler.step", "at": [4], "max_fires": 1}])):
        out = ex.run_requests(_reqs())
    sched = eng._scheduler
    assert sched.audit() == []
    assert all(r.finish_reason for r in out)
    eng.shutdown()


def test_cost_ledger_kill_switch_token_identical(monkeypatch):
    """LMRS_COST_LEDGER=0: outputs byte-identical, no usage blocks, no
    ledger state — the switch is inert on everything but the bill."""
    from lmrs_tpu.engine.jax_engine import JaxEngine

    def run():
        eng = JaxEngine(_cfg(mixed_batch=True), tiny_model())
        out = eng.generate_batch(_reqs())
        sched = eng._scheduler
        assert sched.audit() == []
        texts = [(r.text, r.finish_reason, r.completion_tokens)
                 for r in out]
        usages = [r.usage for r in out]
        rep = sched.metrics_report()
        eng.shutdown()
        return texts, usages, rep

    monkeypatch.setenv("LMRS_COST_LEDGER", "0")
    texts_off, usages_off, rep_off = run()
    assert all(u is None for u in usages_off)
    assert rep_off["cost"] == {"enabled": False}
    monkeypatch.setenv("LMRS_COST_LEDGER", "1")
    texts_on, usages_on, rep_on = run()
    assert all(u is not None for u in usages_on)
    assert rep_on["cost"]["enabled"] is True
    assert texts_on == texts_off


# --------------------------------------------------------- SLO unit tests


def _slo(clock, **kw):
    # hold_s > slow_s so the damping window is observable: samples age
    # out of both burn windows while the dwell clock still holds
    base = dict(enabled=True, fast_s=10.0, slow_s=20.0, hold_s=30.0,
                min_events=2, clock=clock,
                specs=(SLOSpec("error_rate", "rate", 0.1),
                       SLOSpec("ttft_p95_ms", "latency_p95", 100.0)))
    base.update(kw)
    return SLOEngine(**base)


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_slo_state_machine_transitions_and_damping():
    clk = _Clock()
    slo = _slo(clk)
    # healthy traffic: ok
    for _ in range(4):
        slo.observe_ttft(0.01)
        slo.note_result("stop", tokens=10)
    assert slo.report()["state"] == "ok"
    # latency breach in both windows -> warn (burn 1.5)
    clk.t += 1
    for _ in range(8):
        slo.observe_ttft(0.150)
    assert slo.report()["state"] == "warn"
    # heavy breach -> critical (upgrade is immediate)
    for _ in range(20):
        slo.observe_ttft(0.500)
    assert slo.report()["state"] == "critical"
    # samples age out of the windows, but damping HOLDS the state until
    # hold_s elapses — no strobing back to ok on the first clean second
    clk.t += 25  # every sample left both windows, dwell (30s) still held
    doc = slo.report()
    assert doc["raw_state"] == "ok"
    assert doc["state"] == "critical", "downgrade must wait out hold_s"
    clk.t += 11  # dwell elapsed: the damped downgrade lands
    assert slo.report()["state"] == "ok"


def test_slo_rate_spec_min_volume_guard():
    clk = _Clock()
    slo = _slo(clk, min_events=4)
    slo.note_result("error", error="boom")  # 1/1 = 100% error rate...
    assert slo.report()["state"] == "ok"  # ...but below min volume
    for _ in range(5):
        slo.note_result("error", error="boom")
    assert slo.report()["state"] == "critical"


def test_slo_latency_specs_guard_volume_and_cold_outlier():
    """A lone cold-compile TTFT sample must not page: below min_events
    latency specs burn 0, and below 20 samples (where p95 == max) the
    single worst sample is dropped — while a host whose samples are ALL
    slow still breaches."""
    clk = _Clock()
    slo = _slo(clk)  # min_events=2, ttft target 100ms
    slo.observe_ttft(30.0)  # one 30s cold-compile sample
    assert slo.report()["state"] == "ok"  # below min volume
    for _ in range(3):
        slo.observe_ttft(0.01)
    # 4 samples: the cold outlier is dropped, healthy p95 remains
    assert slo.report()["state"] == "ok"
    for _ in range(19):
        slo.observe_ttft(0.500)  # genuinely degraded: every sample slow
    assert slo.report()["state"] == "critical"


def test_slo_critical_fires_postmortem(tmp_path, monkeypatch):
    monkeypatch.setenv("LMRS_POSTMORTEM_DIR", str(tmp_path))
    monkeypatch.setenv("LMRS_POSTMORTEM_MIN_S", "0")
    clk = _Clock()
    slo = _slo(clk, metrics_cb=lambda: {"x": 1})
    for _ in range(6):
        slo.note_result("error", error="boom")
    assert slo.report()["state"] == "critical"
    dumps = list(tmp_path.glob("postmortem-slo-*.json"))
    assert dumps, "critical transition must dump an 'slo' postmortem"
    from lmrs_tpu.obs import validate_postmortem_file

    doc = validate_postmortem_file(dumps[0])
    assert doc["reason"] == "slo"
    assert doc["extra"]["state"] == "critical"


def test_slo_disabled_pins_ok():
    slo = SLOEngine(enabled=False)
    slo.note_result("error", error="boom")
    assert slo.report() == {"enabled": False, "state": "ok", "specs": {}}


def test_slo_spec_env_overrides(monkeypatch):
    from lmrs_tpu.obs.slo import specs_from_env

    monkeypatch.setenv("LMRS_SLO_SPEC",
                       '{"ttft_p95_ms": 55, "bogus": 1, "error_rate": "x"}')
    specs = {s.name: s for s in specs_from_env()}
    assert specs["ttft_p95_ms"].target == 55.0
    assert specs["error_rate"].target == 0.05  # bad value kept default


# ------------------------------------------------- serving / fleet flows


def _post(port, body, headers=None, path="/v1/chat/completions"):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("POST", path, json.dumps(body),
              {"Content-Type": "application/json", **(headers or {})})
    r = c.getresponse()
    out = json.loads(r.read())
    c.close()
    return r.status, out


def _get(port, path):
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    c.request("GET", path)
    r = c.getresponse()
    out = json.loads(r.read())
    c.close()
    return r.status, out


def test_tenant_propagates_router_to_backends_and_usage_sums():
    """X-LMRS-Tenant minted at the front server rides router forwards to
    the backends' ledgers; fleet /v1/usage per-tenant rollups sum to the
    router-reported totals exactly."""
    from lmrs_tpu.serving.router import RouterEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    servers = [EngineHTTPServer(MockEngine(seed=0), port=0)
               for _ in range(2)]
    for s in servers:
        s.start_background()
    router = RouterEngine([f"127.0.0.1:{s.port}" for s in servers],
                          timeout_s=30.0)
    front = EngineHTTPServer(router, port=0)
    front.start_background()
    try:
        for i in range(6):
            st, out = _post(front.port, {
                "messages": [{"role": "user",
                              "content": f"summarize item {i} with "
                                         "plenty of deterministic words "
                                         "in the transcript body."}],
                "max_tokens": 32},
                headers={"X-LMRS-Tenant": f"team{i % 2}"})
            assert st == 200
            cost = out["usage"]["cost"]
            assert cost["tenant"] == f"team{i % 2}"
            assert cost["device_seconds"] > 0
        st, fleet = _get(front.port, "/v1/usage")
        assert st == 200 and fleet["enabled"] and fleet.get("fleet")
        assert set(fleet["tenants"]) == {"team0", "team1"}
        assert sum(r["requests"] for r in fleet["tenants"].values()) == 6
        tenant_dev = sum(r["device_seconds"]
                         for r in fleet["tenants"].values())
        assert abs(tenant_dev - fleet["totals"]["device_seconds"]) < 1e-9
        # host pages sum to the fleet page too
        host_dev = 0.0
        for s in servers:
            st, hu = _get(s.port, "/v1/usage")
            assert st == 200
            host_dev += hu["totals"].get("device_seconds", 0.0)
        assert abs(host_dev - fleet["totals"]["device_seconds"]) < 1e-9
    finally:
        for s in servers + [front]:
            s.shutdown()
        router.shutdown()


def test_tenant_rides_disagg_handoff_legs():
    """Both disaggregation legs bill to the SAME tenant: the payload
    carries the label across the pod boundary (like the trace id)."""
    from lmrs_tpu.serving.router import RouterEngine
    from lmrs_tpu.serving.server import EngineHTTPServer

    pre = EngineHTTPServer(MockEngine(seed=0), port=0, role="prefill")
    dec = EngineHTTPServer(MockEngine(seed=0), port=0, role="decode")
    for s in (pre, dec):
        s.start_background()
    router = RouterEngine([], timeout_s=30.0,
                          prefill_hosts=[f"127.0.0.1:{pre.port}"],
                          decode_hosts=[f"127.0.0.1:{dec.port}"])
    front = EngineHTTPServer(router, port=0)
    front.start_background()
    try:
        st, out = _post(front.port, {
            "messages": [{"role": "user",
                          "content": "a transcript body long enough to "
                                     "hand off between the two pods "
                                     "with several sentences in it."}],
            "max_tokens": 48}, headers={"X-LMRS-Tenant": "acme"})
        assert st == 200, out
        st, du = _get(dec.port, "/v1/usage")
        assert "acme" in du["tenants"], du
    finally:
        for s in (pre, dec, front):
            s.shutdown()
        router.shutdown()


def test_job_tenant_survives_journal_recovery(tmp_path):
    """The tenant persists in the job journal header: a manager restart
    keeps billing the resumed job to the original tenant."""
    from lmrs_tpu.jobs.manager import JobManager

    tx = {"segments": [{"speaker": "A", "start_time": 0.0,
                        "end_time": 30.0,
                        "text": "a meeting about ledger recovery with "
                                "enough words to chunk properly."}]}
    m1 = JobManager(MockEngine(seed=0), tmp_path, start_worker=False)
    job = m1.submit(tx, tenant="acme")
    assert job.tenant == "acme"
    m1.run_job(job)
    assert job.status in ("done", "degraded")
    assert job.usage.get("requests", 0) > 0
    assert m1.status_doc(job)["usage"]["requests"] > 0
    m1.shutdown()
    m2 = JobManager(MockEngine(seed=0), tmp_path, start_worker=False)
    m2.recover()
    j2 = m2.get(job.job_id)
    assert j2 is not None and j2.tenant == "acme"
    assert m2.status_doc(j2)["tenant"] == "acme"
    m2.shutdown()
    # anonymous submits bill to the job's own identity
    m3 = JobManager(MockEngine(seed=0), tmp_path / "b", start_worker=False)
    j3 = m3.submit(tx)
    assert j3.tenant == f"job:{j3.job_id[:24]}"
    m3.shutdown()


def test_session_tenant_and_usage_rollup(tmp_path):
    from lmrs_tpu.live import SessionManager

    mgr = SessionManager(MockEngine(seed=0), tmp_path)
    s = mgr.create(tenant="acme")
    mgr.append(s.session_id, [{"speaker": "A", "start": 0.0, "end": 60.0,
                               "text": "live content to summarize with "
                                       "plenty of words in it now."}])
    mgr.refresh(s.session_id)
    doc = mgr.status_doc(s)
    assert doc["tenant"] == "acme"
    assert doc["usage"]["requests"] > 0
    mgr.shutdown()


def test_usage_501_without_ledger_hook():
    from lmrs_tpu.serving.server import EngineHTTPServer

    class Bare:
        def generate_batch(self, reqs, on_result=None, on_tokens=None):
            return [GenerationResult(request_id=r.request_id)
                    for r in reqs]

        def shutdown(self):
            pass

        def engine_metrics(self):
            return {}

    srv = EngineHTTPServer(Bare(), port=0)
    srv.start_background()
    try:
        st, out = _get(srv.port, "/v1/usage")
        assert st == 501
    finally:
        srv.shutdown()


def test_wire_cost_block_absent_with_kill_switch(monkeypatch):
    """LMRS_COST_LEDGER=0 end-to-end: the wire usage dict is exactly the
    pre-ledger shape and the text is identical."""
    from lmrs_tpu.serving.server import EngineHTTPServer

    body = {"messages": [{"role": "user",
                          "content": "kill switch wire parity check with "
                                     "some deterministic content."}],
            "max_tokens": 24}

    def run():
        srv = EngineHTTPServer(MockEngine(seed=0), port=0)
        srv.start_background()
        try:
            return _post(srv.port, body)
        finally:
            srv.shutdown()

    monkeypatch.setenv("LMRS_COST_LEDGER", "1")
    st_on, on = run()
    monkeypatch.setenv("LMRS_COST_LEDGER", "0")
    st_off, off = run()
    assert st_on == st_off == 200
    assert "cost" in on["usage"] and "cost" not in off["usage"]
    assert on["choices"][0]["message"] == off["choices"][0]["message"]
    assert set(off["usage"]) == {"prompt_tokens", "completion_tokens",
                                 "total_tokens"}


# --------------------------------------------------- SLO-aware routing A/B


def _slo_fleet(n=3, degraded_latency=0.08):
    from lmrs_tpu.serving.server import EngineHTTPServer

    servers = []
    for i in range(n):
        eng = MockEngine(seed=0,
                         latency_s=degraded_latency if i == 0 else 0.0)
        eng.slo = SLOEngine(
            enabled=True, fast_s=30.0, slow_s=30.0, hold_s=5.0,
            specs=(SLOSpec("ttft_p95_ms", "latency_p95", 50.0),))
        servers.append(EngineHTTPServer(eng, port=0))
    for s in servers:
        s.start_background()
    return servers


def _run_slo_arm(servers, routed: bool):
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine([f"127.0.0.1:{s.port}" for s in servers],
                          timeout_s=30.0, prefix_route=False,
                          slo_route=routed, summary_ttl_s=0.4)
    # warm SLO windows past the latency min-sample guard (min_events
    # per host) + the router's summary cache
    for k in range(4 * len(servers)):
        router.generate_batch([GenerationRequest(
            prompt=f"warmup {k}", request_id=900 + k, temperature=0.0,
            max_new_tokens=8)])
        time.sleep(0.04)
    time.sleep(0.5)
    served0 = {h.netloc: h.served for h in router.hosts}
    texts = {}
    for i in range(18):
        req = GenerationRequest(
            prompt=f"measured request {i} deterministic body words.",
            request_id=i, temperature=0.0, max_new_tokens=24)
        res = router.generate_batch([req])[0]
        assert res.error is None
        texts[req.prompt] = res.text
        time.sleep(0.01)
    served = {h.netloc: h.served - served0[h.netloc]
              for h in router.hosts}
    degraded = router.hosts[0].netloc
    share = served[degraded] / max(sum(served.values()), 1)
    router.shutdown()
    return share, texts


def test_slo_routing_sheds_degraded_host_token_identical():
    """The ISSUE 15 acceptance A/B: one host forced into warn by its own
    latency samples loses traffic share under LMRS_SLO_ROUTE while
    aggregate outputs stay token-identical."""
    servers = _slo_fleet()
    try:
        share_off, texts_off = _run_slo_arm(servers, routed=False)
    finally:
        for s in servers:
            s.shutdown()
    servers = _slo_fleet()
    try:
        share_on, texts_on = _run_slo_arm(servers, routed=True)
    finally:
        for s in servers:
            s.shutdown()
    assert share_on < share_off, (share_on, share_off)
    assert texts_on == texts_off


def test_slo_route_kill_switch_keeps_ordering(monkeypatch):
    """slo_route=False never consults SLO state: _targets ordering is
    byte-identical to the pre-SLO router even with a critical host."""
    from lmrs_tpu.serving.router import RouterEngine

    router = RouterEngine(["h1:1", "h2:2"], timeout_s=1.0,
                          slo_route=False)
    with router._summary_lock:
        router._summaries["h1:1"] = {"at": router._clock(), "map": {},
                                     "slo": "critical"}
    order = [h.netloc for h in router._targets(0)]
    assert order == ["h1:1", "h2:2"]  # critical host NOT demoted
    router.slo_route = True
    order = [h.netloc for h in router._targets(0)]
    assert order == ["h2:2", "h1:1"]
    assert router._slo_penalized == 1
    router.shutdown()


# ------------------------------------------------------------ perf sentry


def test_perf_sentry_report_mode_on_repo_history():
    p = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "perf_sentry.py"),
         "--report"], capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, p.stderr
    rep = json.loads(p.stdout)
    assert rep["object"] == "perf_sentry"
    assert "BENCH" in rep["families"]


def test_perf_sentry_catches_planted_regression(tmp_path):
    for i, v in enumerate([10.0, 10.2, 10.1], 1):
        (tmp_path / f"BENCH_r0{i}.json").write_text(json.dumps(
            {"rc": 0, "parsed": {"value": v, "detail": {
                "model": "bench-1b", "chunks_per_sec": v,
                "decode_step_ms": 6.5}}}))
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(
        {"rc": 0, "parsed": {"value": 6.0, "detail": {
            "model": "bench-1b", "chunks_per_sec": 6.0,
            "decode_step_ms": 10.5}}}))
    p = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "perf_sentry.py"),
         "--dir", str(tmp_path)], capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 1
    rep = json.loads(p.stdout)
    names = {r["metric"] for r in rep["regressions"]}
    assert names == {"chunks_per_sec", "decode_step_ms"}
    # report mode reports the same regressions but exits 0 (the CI arm)
    p2 = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "perf_sentry.py"),
         "--dir", str(tmp_path), "--report"],
        capture_output=True, text=True, cwd=REPO)
    assert p2.returncode == 0
    assert json.loads(p2.stdout)["status"] == "regression"


def test_perf_sentry_improvement_not_flagged(tmp_path):
    for i, v in enumerate([10.0, 10.2, 14.0], 1):
        (tmp_path / f"BENCH_r0{i}.json").write_text(json.dumps(
            {"rc": 0, "parsed": {"value": v, "detail": {
                "model": "bench-1b", "chunks_per_sec": v}}}))
    p = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "perf_sentry.py"),
         "--dir", str(tmp_path)], capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, p.stdout
