"""Continuous-batching scheduler tests (CPU, tiny model)."""

import jax
import numpy as np
import pytest

from lmrs_tpu.config import EngineConfig, ModelConfig
from lmrs_tpu.engine.api import GenerationRequest
from lmrs_tpu.engine.jax_engine import JaxEngine


def tiny_model():
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                       hidden_dim=128, max_seq_len=256, dtype="float32")


@pytest.fixture(scope="module")
def cont_engine():
    ec = EngineConfig(backend="jax", scheduler="continuous", max_tokens=24,
                      max_batch_slots=2, seed=0)
    return JaxEngine(ec, tiny_model())


def test_more_requests_than_slots(cont_engine):
    """6 requests through 2 slots: slots must be recycled, all complete,
    order preserved."""
    reqs = [GenerationRequest(prompt=f"item {i} " * (i + 1), request_id=i,
                              temperature=0.8, max_new_tokens=8 + i)
            for i in range(6)]
    out = cont_engine.generate_batch(reqs)
    assert [r.request_id for r in out] == list(range(6))
    for i, r in enumerate(out):
        assert r.error is None
        assert r.completion_tokens <= 8 + i  # budget respected exactly
    m = cont_engine._scheduler.metrics
    assert m["prefill_tokens"] > 0
    assert m["decode_tokens"] > 0
    assert m["decode_dispatches"] > 0


def test_mixed_lengths_interleave(cont_engine):
    """A short and a long request share the batch; the short one's slot is
    reused while the long one still decodes."""
    reqs = [
        GenerationRequest(prompt="short", request_id=0, temperature=0.5, max_new_tokens=2),
        GenerationRequest(prompt="long " * 30, request_id=1, temperature=0.5, max_new_tokens=24),
        GenerationRequest(prompt="third", request_id=2, temperature=0.5, max_new_tokens=2),
    ]
    out = cont_engine.generate_batch(reqs)
    assert all(r.error is None for r in out)
    assert out[0].completion_tokens <= 2
    assert out[2].completion_tokens <= 2


def test_greedy_matches_static_scheduler():
    """Same greedy request through static and continuous scheduling must
    produce the same text (scheduling policy must not change results)."""
    mc = tiny_model()
    req = GenerationRequest(prompt="the quick brown fox", temperature=0.0,
                            max_new_tokens=12)
    static = JaxEngine(EngineConfig(backend="jax", scheduler="static",
                                    max_tokens=12, max_batch_slots=2, seed=0), mc)
    a = static.generate_batch([req])[0]
    cont = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                  max_tokens=12, max_batch_slots=2, seed=0), mc)
    b = cont.generate_batch([req])[0]
    assert a.text == b.text


def test_chunked_prefill_matches_fresh():
    """A prompt longer than prefill_chunk runs the windowed continuation
    path; greedy output must be identical to whole-prompt prefill."""
    mc = tiny_model()
    req = GenerationRequest(prompt="alpha beta gamma " * 12, temperature=0.0,
                            max_new_tokens=10)
    whole = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                   max_tokens=10, max_batch_slots=2, seed=0,
                                   prefill_chunk=4096), mc)
    a = whole.generate_batch([req])[0]
    chunked = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                     max_tokens=10, max_batch_slots=2, seed=0,
                                     prefill_chunk=64), mc)
    b = chunked.generate_batch([req])[0]
    assert a.text == b.text
    # the chunked run must actually have taken the window path
    assert chunked._scheduler._prefill_window_fns, "window path not exercised"


def test_chunked_prefill_piggybacks_decode():
    """While a long prompt prefills chunk by chunk, an already-active short
    request keeps decoding — and prefilling pages are never corrupted by
    decode's dummy writes (outputs stay identical to isolated runs)."""
    mc = tiny_model()
    ec = EngineConfig(backend="jax", scheduler="continuous", max_tokens=12,
                      max_batch_slots=2, seed=3, prefill_chunk=64)
    eng = JaxEngine(ec, mc)
    short = GenerationRequest(prompt="short prompt", request_id=0,
                              temperature=0.0, max_new_tokens=12)
    long_ = GenerationRequest(prompt="delta epsilon zeta " * 12, request_id=1,
                              temperature=0.0, max_new_tokens=12)
    together = eng.generate_batch([short, long_])

    solo_a = JaxEngine(ec, mc).generate_batch([short])[0]
    solo_b = JaxEngine(ec, mc).generate_batch([long_])[0]
    assert together[0].text == solo_a.text
    assert together[1].text == solo_b.text


def test_single_slot_serializes():
    mc = tiny_model()
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=4, max_batch_slots=1, seed=1), mc)
    reqs = [GenerationRequest(prompt=f"r{i}", request_id=i, temperature=0.3,
                              max_new_tokens=4) for i in range(3)]
    out = eng.generate_batch(reqs)
    assert [r.request_id for r in out] == [0, 1, 2]
    assert all(r.error is None for r in out)


def test_engine_metrics_report(cont_engine):
    """engine_metrics() exposes derived serving metrics with sane ranges."""
    reqs = [GenerationRequest(prompt="metrics probe", request_id=0,
                              max_new_tokens=6)]
    cont_engine.generate_batch(reqs)
    em = cont_engine.engine_metrics()
    assert em["prefill_tokens"] > 0 and em["decode_tokens"] > 0
    assert em["prefill_tokens_per_sec"] > 0
    assert em["decode_tokens_per_sec"] > 0
    assert 0.0 < em["mean_decode_occupancy"] <= 1.0
    assert 0.0 < em["peak_kv_page_utilization"] <= 1.0
    assert em["scheduler_seconds"] > 0
    # device-wait attribution: every run() fetch is charged via _timed_get,
    # so a run that generated tokens must show blocked time, and the split
    # must stay within the scheduler wall (host share clamped >= 0)
    assert em["blocked_seconds"] > 0
    assert em["host_seconds"] >= 0
    assert em["blocked_seconds"] <= em["scheduler_seconds"] + 1e-6


def test_latency_percentiles_in_metrics(cont_engine):
    """TTFT and decode-block-gap percentiles (VERDICT r4 item 5) surface
    in metrics_report with sane values, and reset_latency_stats clears
    the sample windows."""
    sched = cont_engine._scheduler
    sched.reset_latency_stats()
    reqs = [GenerationRequest(prompt=f"latency probe {i}", request_id=i,
                              temperature=0.7, max_new_tokens=10)
            for i in range(3)]
    cont_engine.generate_batch(reqs)
    em = cont_engine.engine_metrics()
    ttft = em["ttft_ms"]
    # every fresh request contributes exactly one TTFT sample
    assert ttft is not None and ttft["n"] == 3
    assert 0.0 < ttft["p50"] <= ttft["p90"] <= ttft["p99"]
    # 10 new tokens through default decode_block=8 -> >= 2 dispatches per
    # wave -> at least one inter-dispatch gap
    gap = em["decode_block_gap_ms"]
    assert gap is not None and gap["n"] >= 1
    assert 0.0 < gap["p50"] <= gap["p99"]
    assert em["stalls"] >= 0 and em["cancelled"] >= 0
    sched.reset_latency_stats()
    em2 = cont_engine.engine_metrics()
    assert em2["ttft_ms"] is None and em2["decode_block_gap_ms"] is None


def test_mock_engine_metrics_empty():
    from lmrs_tpu.engine.mock import MockEngine

    assert MockEngine().engine_metrics() == {}


def test_ragged_kernel_failure_degrades_to_xla(cont_engine):
    """If the ragged Pallas kernel can't lower on this platform, the decode
    dispatch must fall back to the XLA gather path, not fail the batch."""
    sched = cont_engine._scheduler
    sched._use_ragged = True  # force the kernel on CPU, where it can't lower
    sched._decode_fns.clear()
    # drop run-history: the fallback (correctly) only triggers on shapes that
    # have never executed — a failure on a proven shape re-raises
    sched._ran_ok = {k for k in sched._ran_ok if k[0] != "decode"}
    try:
        out = cont_engine.generate_batch(
            [GenerationRequest(prompt="fallback probe", request_id=0,
                               max_new_tokens=4)])
    finally:
        sched._use_ragged = False
        sched._decode_fns.clear()
    assert out[0].error is None
    assert out[0].completion_tokens > 0


def test_tp_sharded_continuous_serving_matches_single_device():
    """Continuous-batching map over a tp=2 mesh: params AND the paged KV
    pool shard on the head axis; greedy output must equal single-device
    (BASELINE config #3's architecture, scaled to the virtual mesh)."""
    from lmrs_tpu.config import MeshConfig

    reqs = [GenerationRequest(prompt=f"tensor parallel serving probe {i} " * 6,
                              request_id=i, max_new_tokens=10)
            for i in range(3)]
    single = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                    max_tokens=16, max_batch_slots=2, seed=0),
                       tiny_model())
    want = [r.text for r in single.generate_batch(reqs)]
    single.shutdown()

    tp = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                max_tokens=16, max_batch_slots=2, seed=0),
                   tiny_model(), mesh_cfg=MeshConfig(dp=1, tp=2))
    kv = tp._scheduler.cache.k
    # page-major pool [L*P, K, ps, hd]: kv heads shard on axis 1
    assert kv.sharding.shard_shape(kv.shape)[1] == tiny_model().n_kv_heads // 2
    got = [r.text for r in tp.generate_batch(reqs)]
    tp.shutdown()
    assert got == want


def test_tp_sharded_kernels_continuous_serving(monkeypatch):
    """TP serving on the KERNEL path (VERDICT r1 item 2): with
    LMRS_FORCE_KERNELS=interpret the ragged decode + flash prefill Pallas
    kernels run via shard_map over the tp axis (interpret mode on the CPU
    mesh); greedy output must match the single-device XLA path and no
    runtime fallback may fire."""
    from lmrs_tpu.config import MeshConfig

    mc = ModelConfig(vocab_size=512, dim=512, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=256, max_seq_len=512,
                     dtype="float32")
    assert mc.hd == 128  # kernel-eligible head dim
    ec = lambda: EngineConfig(backend="jax", scheduler="continuous",
                              max_tokens=6, max_batch_slots=2, seed=0,
                              decode_block=3)
    # prompts long enough (>=256 byte tokens) to take the flash prefill path
    reqs = [GenerationRequest(prompt=f"tp kernel serving probe {i} " * 12,
                              request_id=i, temperature=0.0, max_new_tokens=6)
            for i in range(3)]

    single = JaxEngine(ec(), mc)
    assert not single._scheduler._use_ragged  # CPU: XLA fallback path
    want = [r.text for r in single.generate_batch(reqs)]
    single.shutdown()

    monkeypatch.setenv("LMRS_FORCE_KERNELS", "interpret")
    tp = JaxEngine(ec(), mc, mesh_cfg=MeshConfig(dp=1, tp=2))
    sched = tp._scheduler
    assert sched._use_ragged and sched._use_flash
    got = [r.text for r in tp.generate_batch(reqs)]
    # no silent degradation: the kernels must have survived the whole run
    assert sched._use_ragged and sched._use_flash
    tp.shutdown()
    assert got == want


def test_packed_prefill_matches_unpacked(monkeypatch):
    """Packed prompt prefill (VERDICT r1 item 3): same-wave fresh prompts
    concatenate into one [1, S] segment-masked dispatch; greedy output must
    be identical to per-prompt prefill (cross-segment leakage would change
    it), and the packed program must actually have run."""
    mc = tiny_model()
    reqs = [GenerationRequest(prompt=f"pack probe {i} " * (2 + 3 * i),
                              request_id=i, temperature=0.0, max_new_tokens=8)
            for i in range(4)]
    ec = lambda: EngineConfig(backend="jax", scheduler="continuous",
                              max_tokens=8, max_batch_slots=4, seed=0)
    monkeypatch.setenv("LMRS_PACK_PREFILL", "0")
    plain = JaxEngine(ec(), mc)
    want = [r.text for r in plain.generate_batch(reqs)]
    plain.shutdown()

    monkeypatch.setenv("LMRS_PACK_PREFILL", "1")
    packed = JaxEngine(ec(), mc)
    got = [r.text for r in packed.generate_batch(reqs)]
    assert packed._scheduler._packed_prefill_fns, "packed path not exercised"
    packed.shutdown()
    assert got == want


def test_packed_prefill_with_tp_kernels(monkeypatch):
    """Packing composes with the TP kernel path: segment-masked flash
    prefill via shard_map (interpret) must match the single-device
    unpacked XLA run."""
    from lmrs_tpu.config import MeshConfig

    mc = ModelConfig(vocab_size=512, dim=512, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=256, max_seq_len=1024,
                     dtype="float32")
    reqs = [GenerationRequest(prompt=f"tp pack probe {i} " * 12, request_id=i,
                              temperature=0.0, max_new_tokens=4)
            for i in range(3)]
    ec = lambda: EngineConfig(backend="jax", scheduler="continuous",
                              max_tokens=4, max_batch_slots=4, seed=0,
                              decode_block=2, prefill_chunk=1024)
    monkeypatch.setenv("LMRS_PACK_PREFILL", "0")
    single = JaxEngine(ec(), mc)
    want = [r.text for r in single.generate_batch(reqs)]
    single.shutdown()

    monkeypatch.setenv("LMRS_PACK_PREFILL", "1")
    monkeypatch.setenv("LMRS_FORCE_KERNELS", "interpret")
    tp = JaxEngine(ec(), mc, mesh_cfg=MeshConfig(dp=1, tp=2))
    got = [r.text for r in tp.generate_batch(reqs)]
    assert tp._scheduler._packed_prefill_fns, "packed path not exercised"
    assert tp._scheduler._use_flash, "flash kernel silently degraded"
    tp.shutdown()
    assert got == want


def test_ring_prefill_serving_cp_matches_single_device():
    """Cache-aware ring prefill (VERDICT r1 item 5, SURVEY §5.7 tier b):
    under an sp=4 mesh, a long chunk's fresh prefill runs ring attention
    with the sequence sharded over sp while K/V scatter into the page pool;
    greedy output must match the single-device run (decode then reads the
    pages as usual)."""
    from lmrs_tpu.config import MeshConfig

    mc = ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=2048,
                     dtype="float32")
    # one LONG chunk (~1.5k tokens) + a short one sharing the stream
    reqs = [GenerationRequest(prompt="long context line " * 80, request_id=0,
                              temperature=0.0, max_new_tokens=8),
            GenerationRequest(prompt="short probe", request_id=1,
                              temperature=0.0, max_new_tokens=8)]
    ec = lambda: EngineConfig(backend="jax", scheduler="continuous",
                              max_tokens=8, max_batch_slots=2, seed=0,
                              prefill_chunk=2048, decode_block=4)
    single = JaxEngine(ec(), mc)
    want = [r.text for r in single.generate_batch(reqs)]
    single.shutdown()

    cp = JaxEngine(ec(), mc, mesh_cfg=MeshConfig(dp=1, tp=1, sp=4))
    sched = cp._scheduler
    assert sched._use_ring, "ring prefill not selected under sp mesh"
    got = [r.text for r in cp.generate_batch(reqs)]
    cp.shutdown()
    assert got == want


def _short_ctx_model():
    # max_seq_len=96 @ page_size=16 -> max_pages_per_slot=6, so a small
    # explicit num_pages is HONORED (the pool floor is 7), making the page
    # budgets in the pressure tests below real
    return ModelConfig(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, hidden_dim=128, max_seq_len=96,
                       dtype="float32")


def test_prompt_only_admission_raises_concurrency():
    """Admission reserves prompt pages only (VERDICT r1 item 6): with 6
    usable pages and ~2-page prompts whose worst-case budget is 3 pages,
    at least 3 slots must run concurrently — worst-case reservation would
    cap at 2."""
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=24, max_batch_slots=4, seed=0,
                                 page_size=16, num_pages=7, decode_block=4),
                    _short_ctx_model())
    assert eng._scheduler.cache.num_pages == 7  # budget honored, not floored
    # ~20 byte-token prompts -> 2 pages each; budget 20+24+4 = 48 -> 3 pages
    reqs = [GenerationRequest(prompt=f"concurrency probe {i}", request_id=i,
                              temperature=0.0, max_new_tokens=24)
            for i in range(4)]
    out = eng.generate_batch(reqs)
    assert all(r.error is None for r in out)
    m = eng._scheduler.metrics
    assert m["peak_active_slots"] >= 3, m
    eng.shutdown()


def test_preemption_under_page_pressure_preserves_output():
    """Under a pool too small for every admitted slot's decode growth, the
    youngest slot is preempted and requeued; every request must still
    complete with output identical to an abundant-pool run (continuation
    re-prefills prompt + generated-so-far), and no deadlock."""
    mc = _short_ctx_model()
    reqs = [GenerationRequest(prompt=f"pressure probe {i} " * 3, request_id=i,
                              temperature=0.0, max_new_tokens=40)
            for i in range(4)]
    roomy = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                   max_tokens=40, max_batch_slots=4, seed=0,
                                   page_size=16, num_pages=1, decode_block=4),
                      mc)
    want = roomy.generate_batch(reqs)
    assert all(r.error is None for r in want)
    roomy.shutdown()

    # 9 usable pages: four ~4-page prompts can't all fit worst-case (~6
    # pages each through a 40-token decode) -> growth collides, preemption
    tight = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                   max_tokens=40, max_batch_slots=4, seed=0,
                                   page_size=16, num_pages=10, decode_block=4),
                      mc)
    assert tight._scheduler.cache.num_pages == 10
    got = tight.generate_batch(reqs)
    m = tight._scheduler.metrics
    tight.shutdown()
    assert all(r.error is None for r in got)
    assert m["preemptions"] > 0, f"pressure never materialized: {m}"
    assert [r.text for r in got] == [r.text for r in want]
    # accounting must not double-count re-prefilled continuation tokens
    for g, w in zip(got, want):
        assert g.prompt_tokens == w.prompt_tokens
        assert g.completion_tokens == w.completion_tokens


def test_roofline_microbench_smoke(cont_engine):
    """The roofline probe shares the compiled-program arg contract with the
    scheduler; this smoke run catches signature drift off-chip (the real
    numbers only mean something on TPU — bench.py)."""
    out = cont_engine._scheduler.roofline_microbench(prefill_reps=2,
                                                     decode_reps=1)
    for key in ("prefill_tokens_per_sec", "decode_tokens_per_sec"):
        assert out[key] > 0, out
    for key in ("model_flops_utilization", "hbm_bw_utilization"):
        # tiny CPU model: utilization rounds to ~0; presence + range only
        assert 0 <= out[key] < 1.5, out
    # pool must be fully released afterwards
    cache = cont_engine._scheduler.cache
    assert cache.allocator.free_count == cache.num_pages - 1


def test_stalled_slot_keeps_first_token():
    """Regression: a slot that finishes prefill but must STALL (pool pages
    held by a mid-prefill neighbor, no preemptable decode victim) must not
    drop its deferred first token — output must equal a roomy-pool run."""
    mc = _short_ctx_model()
    # short prompt (31 ids: 2 pages, but 31+decode_block=35 needs a 3rd)
    # finishes prefill in one chunk and must grow immediately, while the
    # long prompt (2 chunks of 64, 5 pages) is still mid-prefill and not
    # preemptable: 2+5 = all 7 usable pages -> the short slot STALLS
    reqs = [GenerationRequest(prompt="s" * 30, request_id=0,
                              temperature=0.0, max_new_tokens=8),
            GenerationRequest(prompt="x" * 78, request_id=1,
                              temperature=0.0, max_new_tokens=8)]
    ec = lambda npages: EngineConfig(
        backend="jax", scheduler="continuous", max_tokens=8,
        max_batch_slots=2, seed=0, page_size=16, num_pages=npages,
        decode_block=4, prefill_chunk=64)
    roomy = JaxEngine(ec(1), mc)  # worst-case pool: no pressure
    want = [r.text for r in roomy.generate_batch(reqs)]
    roomy.shutdown()

    tight = JaxEngine(ec(8), mc)
    got = [r.text for r in tight.generate_batch(reqs)]
    m = tight._scheduler.metrics
    tight.shutdown()
    assert m["stalls"] > 0, f"stall branch never exercised: {m}"
    assert got == want


def test_pow2_bucket():
    from lmrs_tpu.engine.scheduler import _pow2_bucket

    assert _pow2_bucket(64, 64) == 64
    assert _pow2_bucket(65, 64) == 128
    for n in (1, 64, 100, 1000, 2049, 4096):
        assert _pow2_bucket(n, 64) >= n


def test_compact_batch_drain_matches_full():
    """With few live slots the decode dispatch compacts to a small batch;
    greedy output must be identical to a small-B engine."""
    mc = tiny_model()
    reqs = [GenerationRequest(prompt=f"compact drain probe {i}", request_id=i,
                              temperature=0.0, max_new_tokens=10)
            for i in range(2)]
    small = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                   max_tokens=10, max_batch_slots=2, seed=0), mc)
    want = [r.text for r in small.generate_batch(reqs)]
    small.shutdown()

    wide = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                  max_tokens=10, max_batch_slots=16, seed=0), mc)
    got = [r.text for r in wide.generate_batch(reqs)]
    wide.shutdown()
    assert got == want


def test_on_tokens_streaming_deltas_concat_to_result():
    """on_tokens deltas (one per decode block) must concatenate to exactly
    the final result text, including the stop-sequence trim — the contract
    the SSE front-end's streamed bodies rely on."""
    mc = tiny_model()
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=24, max_batch_slots=2, seed=0,
                                 decode_block=4), mc)
    reqs = [GenerationRequest(prompt=f"stream probe {i}", request_id=i,
                              temperature=0.9, max_new_tokens=24)
            for i in range(3)]
    deltas: dict[int, list[str]] = {}
    calls: list[int] = []

    def on_tokens(rid, text):
        deltas.setdefault(rid, []).append(text)
        calls.append(rid)

    out = eng.generate_batch(reqs, on_tokens=on_tokens)
    for r in out:
        assert r.error is None
        assert "".join(deltas.get(r.request_id, [])) == r.text
    # decode_block=4 over 24 tokens: streaming must be incremental, not one
    # whole-text delta at completion
    assert any(len(v) > 1 for v in deltas.values()), deltas
    eng.shutdown()


def test_on_tokens_streaming_respects_stop_sequences():
    """A streamed request with a stop sequence must never emit text past
    the stop — deltas are cut from the trimmed text."""
    mc = tiny_model()
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=24, max_batch_slots=2, seed=0,
                                 decode_block=4), mc)
    # greedy decode of the tiny random model produces SOME deterministic
    # text; use its own prefix as the stop to guarantee a mid-stream hit
    probe = eng.generate_batch([GenerationRequest(
        prompt="stop probe", temperature=0.0, max_new_tokens=24)])[0]
    assert probe.text
    stop = probe.text[max(0, len(probe.text) // 2):][:3]
    got: list[str] = []
    res = eng.generate_batch(
        [GenerationRequest(prompt="stop probe", temperature=0.0,
                           max_new_tokens=24, stop=(stop,))],
        on_tokens=lambda rid, t: got.append(t))[0]
    assert stop not in res.text
    assert "".join(got) == res.text
    eng.shutdown()


def test_on_tokens_freezes_on_non_prefix_stable_decode():
    """HF-style tokenizers can rewrite earlier characters as tokens arrive
    (cleanup_tokenization_spaces): the stream must FREEZE — never emit
    characters that later change — and the final result text stays
    authoritative (round-3 review finding)."""
    from lmrs_tpu.data.tokenizer import ByteTokenizer

    class UnstableTokenizer(ByteTokenizer):
        """Decodes normally until >8 ids, then rewrites the first char —
        a caricature of HF cleanup's retroactive edits."""

        def decode(self, ids):
            text = super().decode(ids)
            if len(list(ids)) > 8 and text:
                return "#" + text[1:]
            return text

    mc = tiny_model()
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=20, max_batch_slots=1, seed=0,
                                 decode_block=4), mc,
                    tokenizer=UnstableTokenizer())
    got: list[str] = []
    res = eng.generate_batch(
        [GenerationRequest(prompt="prefix stability probe", request_id=0,
                           temperature=0.0, max_new_tokens=20)],
        on_tokens=lambda rid, t: got.append(t))[0]
    eng.shutdown()
    assert res.error is None
    streamed = "".join(got)
    # the retroactive rewrite ('#' at position 0) appears in the FINAL text
    # but must never have been streamed: the stream froze at the last
    # stable prefix instead of emitting characters that later changed
    assert res.text.startswith("#")
    assert "#" not in streamed
    assert streamed  # deltas did flow before the instability hit


def test_max_new_clamped_to_context_window():
    """A decode budget >= max_seq_len must clamp (a negative truncation
    limit previously DUPLICATED the prompt middle or emptied it)."""
    mc = tiny_model()  # max_seq_len 256
    eng = JaxEngine(EngineConfig(backend="jax", scheduler="continuous",
                                 max_tokens=100000, max_batch_slots=1,
                                 seed=0, decode_block=4), mc)
    ids, max_new = eng._scheduler._encode(
        GenerationRequest(prompt="x" * 500, request_id=0,
                          max_new_tokens=100000))
    assert max_new == mc.max_seq_len - 1
    assert 1 <= len(ids) <= mc.max_seq_len - max_new
    res = eng.generate_batch([
        GenerationRequest(prompt="short", request_id=0, temperature=0.0,
                          max_new_tokens=100000)])[0]
    eng.shutdown()
    assert res.error is None
    assert res.completion_tokens <= mc.max_seq_len - 1
