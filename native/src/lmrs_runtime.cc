// lmrs native runtime: data-plane hot loops + KV page allocator (C ABI).
//
// The reference framework is pure Python (SURVEY.md §0: "no native code
// anywhere"); this library is the TPU build's native runtime layer — the
// host-side work that sits on the scheduler/data-plane critical path:
//
//  * text cleaning  — the per-segment regex pass (reference clean_text,
//    preprocessor.py:69-89) re-implemented as a single UTF-8 scan;
//  * token counting — the chunker's hot loop (reference encodes with
//    tiktoken per segment/sentence/clause, big_chunkeroosky.py:83,370,510;
//    SURVEY.md §3.5 hot loop #2), here the approx-counter contract
//    max(codepoints/4, words/2, 1) over batches of strings;
//  * page allocator — LIFO free-list for the paged KV cache
//    (engine/kv_cache.py PageAllocator), O(1) alloc/free, page 0 reserved.
//
// Exact-parity contract with the Python implementations is enforced by
// tests/test_native.py.  Unicode strategy: the whitespace set matches
// Python's str \s exactly (so counting is exact for ALL input); clean_text's
// \w / IGNORECASE semantics are only reproduced exactly for ASCII, so the
// Python binding routes non-ASCII strings to the pure-Python cleaner —
// parity by construction.  The letter-block tables below only matter for
// direct C-ABI callers.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#if defined(_WIN32)
#define LMRS_API extern "C" __declspec(dllexport)
#else
#define LMRS_API extern "C" __attribute__((visibility("default")))
#endif

namespace {

// ---------------------------------------------------------------- UTF-8

// Decode one codepoint starting at s[i]; advances i.  Invalid bytes are
// treated as Latin-1 (one byte, one codepoint) so the scan never stalls.
inline uint32_t decode_cp(const unsigned char* s, size_t n, size_t& i) {
  unsigned char b = s[i];
  if (b < 0x80) { i += 1; return b; }
  if ((b >> 5) == 0x6 && i + 1 < n && (s[i+1] & 0xC0) == 0x80) {
    uint32_t cp = ((b & 0x1F) << 6) | (s[i+1] & 0x3F);
    i += 2; return cp;
  }
  if ((b >> 4) == 0xE && i + 2 < n && (s[i+1] & 0xC0) == 0x80 &&
      (s[i+2] & 0xC0) == 0x80) {
    uint32_t cp = ((b & 0x0F) << 12) | ((s[i+1] & 0x3F) << 6) | (s[i+2] & 0x3F);
    i += 3; return cp;
  }
  if ((b >> 3) == 0x1E && i + 3 < n && (s[i+1] & 0xC0) == 0x80 &&
      (s[i+2] & 0xC0) == 0x80 && (s[i+3] & 0xC0) == 0x80) {
    uint32_t cp = ((b & 0x07) << 18) | ((s[i+1] & 0x3F) << 12) |
                  ((s[i+2] & 0x3F) << 6) | (s[i+3] & 0x3F);
    i += 4; return cp;
  }
  i += 1;
  return b;
}

inline void encode_cp(uint32_t cp, std::string& out) {
  if (cp < 0x80) { out.push_back(char(cp)); return; }
  if (cp < 0x800) {
    out.push_back(char(0xC0 | (cp >> 6)));
    out.push_back(char(0x80 | (cp & 0x3F)));
    return;
  }
  if (cp < 0x10000) {
    out.push_back(char(0xE0 | (cp >> 12)));
    out.push_back(char(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(char(0x80 | (cp & 0x3F)));
    return;
  }
  out.push_back(char(0xF0 | (cp >> 18)));
  out.push_back(char(0x80 | ((cp >> 12) & 0x3F)));
  out.push_back(char(0x80 | ((cp >> 6) & 0x3F)));
  out.push_back(char(0x80 | (cp & 0x3F)));
}

// Python str \s whitespace set.
inline bool is_space_cp(uint32_t cp) {
  switch (cp) {
    case 0x09: case 0x0A: case 0x0B: case 0x0C: case 0x0D: case 0x20:
    case 0x1C: case 0x1D: case 0x1E: case 0x1F:
    case 0x85: case 0xA0: case 0x1680:
    case 0x2028: case 0x2029: case 0x202F: case 0x205F: case 0x3000:
      return true;
    default:
      return cp >= 0x2000 && cp <= 0x200A;
  }
}

// Word char: ASCII alnum/underscore, plus non-ASCII codepoints in the major
// letter blocks (Latin-1/extended, Greek, Cyrillic, Armenian, Hebrew,
// Arabic, Indic, kana, CJK, Hangul).  Symbols/emoji are NOT word chars —
// matching Python's unicode \w on the transcript domain without shipping
// full Unicode category tables.
inline bool is_word_cp(uint32_t cp) {
  if (cp < 0x80) {
    return (cp >= 'a' && cp <= 'z') || (cp >= 'A' && cp <= 'Z') ||
           (cp >= '0' && cp <= '9') || cp == '_';
  }
  if (cp == 0xD7 || cp == 0xF7) return false;  // multiply / divide signs
  if (cp >= 0xC0 && cp <= 0x24F) return true;    // Latin-1 + extended
  if (cp >= 0x370 && cp <= 0x5FF) return true;   // Greek, Cyrillic, Armenian, Hebrew
  if (cp >= 0x600 && cp <= 0x6FF) return true;   // Arabic
  if (cp >= 0x900 && cp <= 0xDFF) return true;   // Indic scripts
  if (cp >= 0x1E00 && cp <= 0x1FFF) return true; // Latin/Greek additional
  if (cp >= 0x3040 && cp <= 0x30FF) return true; // kana
  if (cp >= 0x4E00 && cp <= 0x9FFF) return true; // CJK unified
  if (cp >= 0xAC00 && cp <= 0xD7AF) return true; // Hangul
  return false;
}

inline uint32_t ascii_lower(uint32_t cp) {
  return (cp >= 'A' && cp <= 'Z') ? cp + 32 : cp;
}

struct Run {
  uint32_t start, end;  // [start, end) index range into the codepoint array
  uint8_t cls;          // 0 = other, 1 = space, 2 = word
};

// --------------------------------------------------------- clean_text

// Mirrors lmrs_tpu.data.preprocessor.clean_text:
//   1. \s+ -> " "  and strip;
//   2. \b(\w+)(\s+\1\b)+ -> \1  (case-insensitive immediate-repeat dedup);
//   3. ([.!?,;:])([A-Za-z]) -> "\1 \2".
// `out` is appended to (batch API reuses one buffer); scratch vectors are
// caller-owned to amortize allocations across a batch.
void clean_text_impl(const unsigned char* s, size_t n, std::string& out,
                     std::vector<uint32_t>& cps, std::vector<Run>& runs) {
  if (n == 0) return;
  cps.clear();
  runs.clear();
  cps.reserve(n);
  size_t i = 0;
  uint8_t prev_cls = 255;
  while (i < n) {
    uint32_t cp = decode_cp(s, n, i);
    bool sp = is_space_cp(cp);
    uint8_t cls = sp ? 1 : (is_word_cp(cp) ? 2 : 0);
    if (cls != prev_cls) {
      runs.push_back(Run{uint32_t(cps.size()), uint32_t(cps.size()), cls});
      prev_cls = cls;
    }
    cps.push_back(cp);
    runs.back().end = uint32_t(cps.size());
  }

  auto words_equal_nocase = [&](const Run& a, const Run& b) {
    if (a.end - a.start != b.end - b.start) return false;
    for (uint32_t j = 0; j < a.end - a.start; ++j) {
      if (ascii_lower(cps[a.start + j]) != ascii_lower(cps[b.start + j]))
        return false;
    }
    return true;
  };

  // Pass 1+2 fused: whitespace runs become one space; a word run preceded
  // (through whitespace only) by a case-equal word run is dropped together
  // with that whitespace — the regex consumes "\s+\1", so following text
  // continues flush against the kept word.
  size_t start = 0, end = runs.size();
  while (start < end && runs[start].cls == 1) ++start;  // lstrip
  while (end > start && runs[end - 1].cls == 1) --end;  // rstrip

  size_t emit_from = out.size();
  int last_word = -1;  // index into runs of the word run emitted last
  bool last_emitted_was_word = false;
  bool pending_space = false;
  for (size_t t = start; t < end; ++t) {
    const Run& r = runs[t];
    if (r.cls == 1) {
      pending_space = true;
      continue;
    }
    if (r.cls == 2 && last_word >= 0 && last_emitted_was_word &&
        pending_space && words_equal_nocase(runs[last_word], r)) {
      pending_space = false;
      continue;
    }
    if (pending_space) {
      out.push_back(' ');
      pending_space = false;
    }
    // Pass 3 fused at emission: a word starting [A-Za-z] flush against a
    // kept trailing [.!?,;:] gets the missing space restored.
    if (r.cls == 2 && !pending_space && out.size() > emit_from) {
      char prevb = out.back();
      uint32_t first = cps[r.start];
      if ((prevb == '.' || prevb == '!' || prevb == '?' || prevb == ',' ||
           prevb == ';' || prevb == ':') &&
          ((first >= 'A' && first <= 'Z') || (first >= 'a' && first <= 'z'))) {
        out.push_back(' ');
      }
    }
    for (uint32_t j = r.start; j < r.end; ++j) encode_cp(cps[j], out);
    last_emitted_was_word = (r.cls == 2);
    if (r.cls == 2) last_word = int(t);
  }
}

std::string clean_text_str(const unsigned char* s, size_t n) {
  std::string out;
  out.reserve(n);
  std::vector<uint32_t> cps;
  std::vector<Run> runs;
  clean_text_impl(s, n, out, cps, runs);
  return out;
}

// ------------------------------------------------------ approx counting

// Mirrors ApproxTokenizer.count: max(codepoints // 4, \S+ runs // 2, 1),
// 0 for the empty string.
int64_t count_approx_impl(const unsigned char* s, size_t n) {
  if (n == 0) return 0;
  int64_t cps = 0, words = 0;
  bool in_word = false;
  size_t i = 0;
  while (i < n) {
    uint32_t cp = decode_cp(s, n, i);
    ++cps;
    bool sp = is_space_cp(cp);
    if (!sp && !in_word) { ++words; in_word = true; }
    if (sp) in_word = false;
  }
  int64_t by_chars = cps / 4;
  int64_t by_words = words / 2;
  int64_t best = by_chars > by_words ? by_chars : by_words;
  return best > 1 ? best : 1;
}

// ---------------------------------------------------------- allocator

// Mirrors engine/kv_cache.PageAllocator: LIFO free list initialized
// [num_pages-1 .. 1] (so pages are handed out 1, 2, 3, ... and freed pages
// are reused most-recently-freed-first).  Page 0 is reserved (null page).
// Pages are ref-counted (prefix-cache sharing): alloc hands out refcount 1,
// incref adds a holder, free is a decref that returns the page to the free
// list only at zero — and errors on a page already free (double-free would
// hand one page to two sequences).
struct PageAlloc {
  int32_t num_pages;
  std::vector<int32_t> free_list;
  std::vector<int32_t> refs;  // per-page refcount; 0 == on the free list
  std::mutex mu;
};

// Validate a free/incref batch before ANY mutation: every id in range and
// every page's refcount covering its multiplicity in the call.  Returns 0,
// -2 on a bad id, -3 on a double-free / unowned page.
int32_t check_pages(const PageAlloc* a, const int32_t* pages, int32_t n) {
  for (int32_t i = 0; i < n; ++i) {
    if (pages[i] < 1 || pages[i] >= a->num_pages) return -2;
  }
  for (int32_t i = 0; i < n; ++i) {
    int32_t mult = 0;
    for (int32_t j = 0; j < n; ++j) mult += (pages[j] == pages[i]);
    if (a->refs[pages[i]] < mult) return -3;
  }
  return 0;
}

}  // namespace

// =================================================================== C ABI

LMRS_API int32_t lmrs_abi_version(void) { return 2; }

// ---- text ----

// Clean `in[0..n)` into `out` (capacity out_cap).  Returns the cleaned
// length, or the required capacity as a negative number if out_cap is too
// small (call again with a bigger buffer).  Output never exceeds 2n+1 bytes.
LMRS_API int64_t lmrs_clean_text(const char* in, int64_t n, char* out,
                                 int64_t out_cap) {
  std::string r = clean_text_str(reinterpret_cast<const unsigned char*>(in),
                                 size_t(n));
  if (int64_t(r.size()) > out_cap) return -int64_t(r.size());
  std::memcpy(out, r.data(), r.size());
  return int64_t(r.size());
}

// Batch cleaning over concatenated strings (string i spans
// buf[offsets[i] .. offsets[i+1]); offsets has n+1 entries).  Cleaned
// strings are written back-to-back into `out` with their spans recorded in
// out_offsets (n+1 entries).  Returns 0, or the required capacity as a
// negative number if out_cap is too small.
LMRS_API int64_t lmrs_clean_text_batch(const char* buf, const int64_t* offsets,
                                       int64_t n, char* out, int64_t out_cap,
                                       int64_t* out_offsets) {
  std::string acc;
  acc.reserve(size_t(offsets[n] - offsets[0]) + 16);
  std::vector<uint32_t> cps;
  std::vector<Run> runs;
  out_offsets[0] = 0;
  for (int64_t i = 0; i < n; ++i) {
    clean_text_impl(
        reinterpret_cast<const unsigned char*>(buf + offsets[i]),
        size_t(offsets[i + 1] - offsets[i]), acc, cps, runs);
    out_offsets[i + 1] = int64_t(acc.size());
  }
  if (int64_t(acc.size()) > out_cap) return -int64_t(acc.size());
  std::memcpy(out, acc.data(), acc.size());
  return 0;
}

LMRS_API int64_t lmrs_count_approx(const char* in, int64_t n) {
  return count_approx_impl(reinterpret_cast<const unsigned char*>(in), size_t(n));
}

// Batch counting over concatenated strings: string i spans
// buf[offsets[i] .. offsets[i+1]).  offsets has n+1 entries.
LMRS_API void lmrs_count_approx_batch(const char* buf, const int64_t* offsets,
                                      int64_t n, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    out[i] = count_approx_impl(
        reinterpret_cast<const unsigned char*>(buf + offsets[i]),
        size_t(offsets[i + 1] - offsets[i]));
  }
}

// ---- page allocator ----

LMRS_API void* lmrs_palloc_create(int32_t num_pages) {
  if (num_pages <= 1) return nullptr;  // page 0 reserved; need >= 2
  auto* a = new PageAlloc();
  a->num_pages = num_pages;
  a->free_list.reserve(num_pages - 1);
  for (int32_t p = num_pages - 1; p >= 1; --p) a->free_list.push_back(p);
  a->refs.assign(num_pages, 0);
  return a;
}

LMRS_API void lmrs_palloc_destroy(void* h) {
  delete static_cast<PageAlloc*>(h);
}

LMRS_API int32_t lmrs_palloc_free_count(void* h) {
  auto* a = static_cast<PageAlloc*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  return int32_t(a->free_list.size());
}

// Pop n pages into out at refcount 1.  Returns 0, or -1 if fewer than n
// pages are free (OutOfPages back-pressure; nothing is allocated).
LMRS_API int32_t lmrs_palloc_alloc(void* h, int32_t n, int32_t* out) {
  auto* a = static_cast<PageAlloc*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  if (n < 0 || size_t(n) > a->free_list.size()) return -1;
  for (int32_t i = 0; i < n; ++i) {
    out[i] = a->free_list.back();
    a->free_list.pop_back();
    a->refs[out[i]] = 1;
  }
  return 0;
}

// Release one reference per page; pages reaching refcount 0 return to the
// pool.  Returns 0, -2 on an out-of-range page id, -3 on a double-free /
// unowned page (ids validated before any mutation).
LMRS_API int32_t lmrs_palloc_free(void* h, const int32_t* pages, int32_t n) {
  auto* a = static_cast<PageAlloc*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  int32_t rc = check_pages(a, pages, n);
  if (rc != 0) return rc;
  for (int32_t i = 0; i < n; ++i) {
    if (--a->refs[pages[i]] == 0) a->free_list.push_back(pages[i]);
  }
  return 0;
}

// Add one reference per page (prefix-cache sharing); only live pages may
// gain holders.  Returns 0, -2 on a bad id, -3 on a refcount-0 page.
LMRS_API int32_t lmrs_palloc_incref(void* h, const int32_t* pages,
                                    int32_t n) {
  auto* a = static_cast<PageAlloc*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  int32_t rc = check_pages(a, pages, n);
  if (rc != 0) return rc;
  for (int32_t i = 0; i < n; ++i) ++a->refs[pages[i]];
  return 0;
}

// Current refcount of one page (>= 0), or -2 on an out-of-range id.
LMRS_API int32_t lmrs_palloc_refcount(void* h, int32_t page) {
  auto* a = static_cast<PageAlloc*>(h);
  std::lock_guard<std::mutex> lk(a->mu);
  if (page < 0 || page >= a->num_pages) return -2;
  return a->refs[page];
}
