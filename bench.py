"""Benchmark runner: end-to-end map-reduce summarization throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures chunks/sec for the full pipeline (preprocess -> chunk -> on-device
map inference -> hierarchical reduce) on the reference's 7.4h example
transcript, with the JAX engine running a byte-vocab decoder on whatever
accelerator is available (the driver runs this on one real TPU chip).

vs_baseline: the reference has no published numbers (BASELINE.md); its
implied throughput ceiling with default settings is 5 concurrent API calls at
~20 s/request ≈ 0.25 chunks/sec.  vs_baseline = ours / 0.25.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REFERENCE_BASELINE_CHUNKS_PER_SEC = 0.25

TRANSCRIPT_CANDIDATES = [
    Path("/root/reference/transcript-example.json"),
    Path(__file__).parent / "tests" / "data" / "transcript-example.json",
]


def load_transcript() -> dict:
    for p in TRANSCRIPT_CANDIDATES:
        if p.exists():
            return json.loads(p.read_text())
    # synthesize a ~2h transcript if the fixture is missing
    segs = []
    t = 0.0
    for i in range(3000):
        segs.append({"start": t, "end": t + 2.4,
                     "text": f"Segment {i} discusses milestone {i % 97} of the plan.",
                     "speaker": f"SPEAKER_{i % 2:02d}"})
        t += 2.5
    return {"segments": segs}


def main() -> int:
    from lmrs_tpu.config import (
        ChunkConfig, EngineConfig, ModelConfig, PipelineConfig, ReduceConfig,
    )
    from lmrs_tpu.pipeline import TranscriptSummarizer
    from lmrs_tpu.utils.logging import setup_logging

    setup_logging(quiet=True)
    transcript = load_transcript()

    # ~45M-param byte-vocab decoder: big enough that prefill rides the MXU,
    # small enough to compile fast.  Random weights (no egress for real
    # checkpoints) — throughput-identical to a trained model of this shape.
    # head_dim 128 engages the ragged Pallas decode kernel on TPU.
    model = ModelConfig(
        name="bench-45m", vocab_size=512, dim=512, n_layers=8, n_heads=4,
        n_kv_heads=4, hidden_dim=1536, max_seq_len=4096, dtype="bfloat16",
    )
    cfg = PipelineConfig(
        chunk=ChunkConfig(max_tokens_per_chunk=2048, context_tokens=150,
                          overlap_tokens=0, tokenizer="byte"),
        # decode_block/prefill_chunk sized for high-latency host links
        # (~250 ms/round-trip on tunneled chips): fewer, bigger dispatches,
        # and prefill_chunk > max prompt so every prefill is one fresh
        # flash-attention dispatch (no window-gather continuation path)
        # 24 slots: decode's per-dispatch host RTT amortizes over 3x more
        # rows (measured 3.0 -> 5.2 req/s vs 8 slots on the bench chip)
        # decode_block == max_tokens: a request's whole decode is ONE
        # dispatch (sweep: 8.0 req/s vs 3.6-6.8 for block 64, docs/PERF.md)
        # page_size 512: decode is DMA-latency-bound on per-page fetches;
        # 4x bigger pages halved the per-step cost (8.6 -> 4.2 ms/step,
        # docs/PERF.md; 1024 fails pallas lowering)
        # num_pages=1: pool sizing then takes the B*max_pages_per_slot+1
        # floor (193 pages) instead of the 512-page default that would
        # cost 2.7x the HBM at this page size
        engine=EngineConfig(backend="jax", max_tokens=128, max_batch_slots=24,
                            retry_delay=0.0, seed=0, page_size=512,
                            num_pages=1, decode_block=128, prefill_chunk=4096),
        model=model,
        reduce=ReduceConfig(max_tokens_per_batch=6000),
    )
    s = TranscriptSummarizer(cfg)

    # Warm-up outside the timed region, covering every shape the timed run
    # uses.  900 segments = 53 chunks measured with this chunker config:
    # fills all 24 decode slots (full-width decode + n=B batched prefill)
    # AND pushes the summary total past the reduce batch budget, compiling
    # the HIERARCHICAL reduce programs (batch + final prompts, n=1
    # prefill) — a sub-40-chunk warm-up takes the single-pass reduce and
    # leaves those to compile inside the timed run.
    s.summarize({"segments": transcript["segments"][:900]})

    # counters are cumulative over the summarizer's lifetime; snapshot so
    # the printed detail reflects the timed run only, not warm-up work
    tokens_before = s.executor.total_tokens_used
    failed_before = s.executor.failed_requests

    t0 = time.time()
    stats = s.summarize(transcript)
    wall = time.time() - t0

    chunks = stats["num_chunks"]
    value = chunks / wall
    print(json.dumps({
        "metric": "e2e_map_reduce_chunks_per_sec",
        "value": round(value, 3),
        "unit": "chunks/s",
        "vs_baseline": round(value / REFERENCE_BASELINE_CHUNKS_PER_SEC, 2),
        "detail": {
            "num_chunks": chunks,
            "wall_s": round(wall, 2),
            "map_s": round(stats["stage_times"].get("map", 0.0), 2),
            "reduce_s": round(stats["stage_times"].get("reduce", 0.0), 2),
            "total_tokens": stats["total_tokens_used"] - tokens_before,
            "failed": stats["failed_requests"] - failed_before,
            "model": model.name,
            "backend": "jax",
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
