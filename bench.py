"""Benchmark runner: end-to-end map-reduce summarization throughput at
~1B-param scale, plus device-level roofline numbers.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
The detail block carries the VERDICT-r1 roofline fields: prefill_tokens_per_sec,
decode_tokens_per_sec, model_flops_utilization (prefill MFU vs the chip's bf16
peak), hbm_bw_utilization (decode bytes/step vs the HBM peak) — measured with
RTT-amortized dispatch chains on the device, since wall-clock through the
tunneled host link measures the link, not the chip (docs/PERF.md).

vs_baseline: the reference has no published numbers (BASELINE.md); its implied
throughput ceiling with default settings is 5 concurrent API calls at
~20 s/request ≈ 0.25 chunks/sec.  vs_baseline = ours / 0.25.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

REFERENCE_BASELINE_CHUNKS_PER_SEC = 0.25

TRANSCRIPT_CANDIDATES = [
    Path("/root/reference/transcript-example.json"),
    Path(__file__).parent / "tests" / "data" / "transcript-example.json",
]


def load_transcript() -> dict:
    for p in TRANSCRIPT_CANDIDATES:
        if p.exists():
            return json.loads(p.read_text())
    # synthesize a ~2h transcript if the fixture is missing
    segs = []
    t = 0.0
    for i in range(3000):
        segs.append({"start": t, "end": t + 2.4,
                     "text": f"Segment {i} discusses milestone {i % 97} of the plan.",
                     "speaker": f"SPEAKER_{i % 2:02d}"})
        t += 2.5
    return {"segments": segs}


def _param_count_m(params) -> float:
    from lmrs_tpu.models.transformer import param_count

    return param_count(params) / 1e6


def main() -> int:
    from lmrs_tpu.config import (
        ChunkConfig, EngineConfig, PipelineConfig, ReduceConfig, model_preset,
    )
    from lmrs_tpu.pipeline import TranscriptSummarizer
    from lmrs_tpu.utils.logging import setup_logging

    setup_logging(quiet=True)
    transcript = load_transcript()

    # ~1.03B-param GQA decoder (config.model_preset "bench-1b"): big enough
    # that the bench measures the MXU and HBM, not the host link (the r1
    # 45M model ran at <1% MFU — VERDICT r1 item 1).  Random weights (no
    # egress) — throughput-identical to a trained model of this shape.
    # LMRS_BENCH_MODEL: A/B hook (e.g. "tiny" for a CPU smoke run of the
    # bench harness itself; the driver always runs the default on the chip)
    model = model_preset(os.environ.get("LMRS_BENCH_MODEL", "bench-1b"))
    cfg = PipelineConfig(
        # 1400-token chunks: chunk body (1250) + context header (150) + the
        # ~470-byte map template stay under the scheduler's truncation
        # limit max_seq_len - max_tokens = 1920, so no map prompt is
        # middle-truncated mid-run (at 1600 ~40% of prompts were)
        chunk=ChunkConfig(max_tokens_per_chunk=1400, context_tokens=150,
                          overlap_tokens=0, tokenizer="byte"),
        # Dispatch sizing for a ~250 ms-RTT tunneled chip (docs/PERF.md):
        # 24 slots, decode_block == max_tokens (whole decode in one
        # dispatch), prefill_chunk > max prompt (one fresh dispatch,
        # packed), page_size 512 (decode was DMA-latency-bound on page
        # fetches), num_pages=1 -> worst-case pool floor sizing.
        # quantize="int8": ABBA-measured +5.9% on decode-heavy waves at
        # this scale (weight stream halves; docs/PERF.md round 2).  The
        # LIBRARY default stays bf16 — weight-only int8 is a quality
        # tradeoff a throughput bench need not pay but a user must opt
        # into.
        engine=EngineConfig(backend="jax", max_tokens=128, max_batch_slots=24,
                            retry_delay=0.0, seed=0, page_size=512,
                            num_pages=1, decode_block=128, prefill_chunk=4096,
                            quantize="int8"),
        model=model,
        reduce=ReduceConfig(max_tokens_per_batch=6000),
    )
    s = TranscriptSummarizer(cfg)

    # Warm-up outside the timed region, covering every shape the timed run
    # uses: full decode slots, packed prefill at the capped bucket set,
    # and the hierarchical reduce programs.
    s.summarize({"segments": transcript["segments"][:900]})

    # Device-level roofline on the live engine (RTT-amortized chains).
    # Failure-isolated: the auxiliary detail must never cost the headline.
    sched = s.executor.engine._scheduler
    try:
        roofline = sched.roofline_microbench()
    except Exception as e:  # pragma: no cover - chip-side failure path
        print(f"roofline microbench failed: {e!r}", file=sys.stderr)
        roofline = {"roofline_error": str(e)[:200]}

    # counters are cumulative over the summarizer's lifetime; snapshot so
    # the printed detail reflects the timed run only, not warm-up work
    tokens_before = s.executor.total_tokens_used
    failed_before = s.executor.failed_requests

    t0 = time.time()
    stats = s.summarize(transcript)
    wall = time.time() - t0

    chunks = stats["num_chunks"]
    value = chunks / wall
    print(json.dumps({
        "metric": "e2e_map_reduce_chunks_per_sec",
        "value": round(value, 3),
        "unit": "chunks/s",
        "vs_baseline": round(value / REFERENCE_BASELINE_CHUNKS_PER_SEC, 2),
        "detail": {
            "num_chunks": chunks,
            "wall_s": round(wall, 2),
            "map_s": round(stats["stage_times"].get("map", 0.0), 2),
            "reduce_s": round(stats["stage_times"].get("reduce", 0.0), 2),
            "total_tokens": stats["total_tokens_used"] - tokens_before,
            "failed": stats["failed_requests"] - failed_before,
            "model": model.name,
            "params_m": round(_param_count_m(sched.params), 1),
            "backend": "jax",
            **roofline,
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
