"""Benchmark runner: end-to-end map-reduce summarization throughput at
~1B-param scale, plus device-level roofline numbers.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.
The detail block carries the VERDICT-r1 roofline fields: prefill_tokens_per_sec,
decode_tokens_per_sec, model_flops_utilization (prefill MFU vs the chip's bf16
peak), hbm_bw_utilization (decode bytes/step vs the HBM peak) — measured with
RTT-amortized dispatch chains on the device, since wall-clock through the
tunneled host link measures the link, not the chip (docs/PERF.md).

Un-killable by construction (VERDICT r2 item 1 — BENCH_r02 died rc=1 on a
transient backend-init UNAVAILABLE):

- backend init runs in a watchdogged daemon thread with bounded
  retry/backoff (``clear_backends`` between attempts — a failed init is
  sticky otherwise), so a hung or transiently unreachable TPU tunnel
  cannot hang or crash the bench;
- a global watchdog thread guarantees the one-line JSON is emitted even if
  a device call wedges after init;
- every failure path emits the same one-line JSON with value 0.0 and an
  "error" detail, exit code 0 — the driver always captures a diagnosable
  artifact, never a bare traceback.

The timed region repeats LMRS_BENCH_REPS times (default 3); the headline is
the MEDIAN rep and the detail block carries per-rep values + spread, so a
driver-captured number is distinguishable from link weather (VERDICT r2
weak #5; see memory of 2.4-7.7 chunks/s spread on identical code).

vs_baseline: the reference has no published numbers (BASELINE.md); its implied
throughput ceiling with default settings is 5 concurrent API calls at
~20 s/request ≈ 0.25 chunks/sec (reference llm_executor.py:133-147).
vs_baseline = ours / 0.25.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time
from pathlib import Path

from lmrs_tpu.utils.env import env_float, env_int, env_str

REFERENCE_BASELINE_CHUNKS_PER_SEC = 0.25

TRANSCRIPT_CANDIDATES = [
    Path("/root/reference/transcript-example.json"),
    Path(__file__).parent / "tests" / "data" / "transcript-example.json",
]

_emit_lock = threading.Lock()
_emitted = False
# completed timed reps, appended as they finish: if the watchdog fires
# mid-run (slow link, wedged dispatch after some reps landed), it emits
# the median of what completed instead of throwing the data away
_partial_reps: list[dict] = []


def emit(value: float, detail: dict) -> None:
    """Print the one-line JSON artifact exactly once, whoever gets there
    first (main path, failure path, or watchdog)."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        _emitted = True
        print(json.dumps({
            "metric": "e2e_map_reduce_chunks_per_sec",
            "value": round(value, 3),
            "unit": "chunks/s",
            "vs_baseline": round(value / REFERENCE_BASELINE_CHUNKS_PER_SEC, 2),
            "detail": detail,
        }), flush=True)


def summarize_reps(reps: list[dict]) -> tuple[float, dict]:
    """Headline = MEDIAN rep; detail = the rep NEAREST the median (never
    contradicting the headline) + per-rep values and spread.  The ONE
    summary used by the happy path, the watchdog, and the error path."""
    vals = sorted(r["chunks_per_sec"] for r in reps)
    value = statistics.median(vals)
    row = min(reps, key=lambda r: abs(r["chunks_per_sec"] - value))
    return value, {
        **row,
        "reps": len(reps),
        "rep_chunks_per_sec": [r["chunks_per_sec"] for r in reps],
        "spread": round(vals[-1] - vals[0], 3),
    }


def emit_salvage(note: str) -> None:
    """Emit the best artifact available after a failure: the median of any
    COMPLETED reps (flagged partial) — measured data must never be thrown
    away for a late error — else value 0 with the error alone."""
    reps = list(_partial_reps)  # snapshot: the main thread may append
    if reps:
        value, detail = summarize_reps(reps)
        emit(value, {**detail, "partial": True, "error": note})
    else:
        emit(0.0, {"error": note})


def start_watchdog(deadline_s: float) -> threading.Timer:
    """If the bench wedges on a device call after init, still emit the
    artifact — the median of any COMPLETED reps, else an error — and exit
    cleanly."""
    def fire() -> None:
        emit_salvage(f"watchdog: bench exceeded {deadline_s:.0f}s deadline "
                     "(device call wedged?)")
        sys.stdout.flush()
        os._exit(0)

    t = threading.Timer(deadline_s, fire)
    t.daemon = True
    t.start()
    return t


def acquire_backend() -> tuple[bool, str]:
    """Initialize the JAX backend with bounded retry/backoff, in-process.

    Two transient failure modes, both observed on the tunneled chip
    (BENCH_r02 died on the first): a FAST init error ("backend 'axon'
    UNAVAILABLE") — retried after ``clear_backends`` with backoff — and a
    HANG inside the C++ init.  Init runs in a daemon thread so a hang
    can't wedge the bench: after the total budget we give up and report
    (a second init thread would just block on the same init lock, so a
    hung attempt is joined, never respawned).  Returns (ok, log)."""
    total_budget = env_float("LMRS_BENCH_INIT_TIMEOUT_S", 600.0, lo=1.0)
    attempts = env_int("LMRS_BENCH_BACKEND_ATTEMPTS", 5, lo=1)
    deadline = time.time() + total_budget
    log: list[str] = []

    def try_init(state: dict) -> None:
        try:
            import jax

            from lmrs_tpu.utils.platform import honor_platform_env

            # sitecustomize may force jax_platforms past the env var; the
            # shared helper re-applies an explicit request (CPU smoke path)
            honor_platform_env()
            if state["n"] > 0:
                import jax.extend.backend as jeb
                jeb.clear_backends()  # failed init is sticky otherwise
            d = jax.devices()
            state["ok"] = f"{d[0].platform} x{len(d)}"
        except Exception as e:  # noqa: BLE001 - retried
            state["error"] = repr(e)[:200]

    for i in range(attempts):
        state: dict = {"n": i}
        t0 = time.time()
        th = threading.Thread(target=try_init, args=(state,), daemon=True)
        th.start()
        th.join(timeout=max(1.0, deadline - time.time()))
        if th.is_alive():
            log.append(f"attempt {i + 1}: init still hung after "
                       f"{time.time() - t0:.0f}s (budget {total_budget:.0f}s)")
            return False, "; ".join(log)
        if "ok" in state:
            log.append(f"attempt {i + 1}: ok ({time.time() - t0:.0f}s, "
                       f"{state['ok']})")
            return True, "; ".join(log)
        log.append(f"attempt {i + 1}: {state.get('error', '?')}")
        if i + 1 < attempts and time.time() < deadline:
            time.sleep(min(15.0 * (i + 1), 45.0, max(1.0, deadline - time.time())))
    return False, "; ".join(log)


def load_transcript() -> dict:
    data = None
    for p in TRANSCRIPT_CANDIDATES:
        if p.exists():
            data = json.loads(p.read_text())
            break
    if data is None:
        # synthesize a ~2h transcript if the fixture is missing
        segs = []
        t = 0.0
        for i in range(3000):
            segs.append({"start": t, "end": t + 2.4,
                         "text": f"Segment {i} discusses milestone {i % 97} of the plan.",
                         "speaker": f"SPEAKER_{i % 2:02d}"})
            t += 2.5
        data = {"segments": segs}
    # LMRS_BENCH_SEGMENTS: cap the workload (CPU smoke of the bench harness
    # itself — the driver never sets it, so chip runs get the full fixture)
    cap = env_int("LMRS_BENCH_SEGMENTS", 0, lo=0)
    if cap > 0:
        data = {"segments": data["segments"][:cap]}
    return data


def _param_count_m(params) -> float:
    from lmrs_tpu.models.transformer import param_count

    return param_count(params) / 1e6


def run_bench(trace_out: str | None = None) -> tuple[float, dict]:
    from lmrs_tpu.config import (
        ChunkConfig, EngineConfig, PipelineConfig, ReduceConfig, model_preset,
    )
    from lmrs_tpu.pipeline import TranscriptSummarizer
    from lmrs_tpu.utils.logging import setup_logging

    # logs -> stderr: this process's stdout is the one-JSON-line artifact
    # the driver parses; a WARNING on stdout would corrupt it
    setup_logging(quiet=True, stream=sys.stderr)
    if trace_out:
        from lmrs_tpu.obs import enable_tracing

        enable_tracing()
    transcript = load_transcript()

    # ~1.03B-param GQA decoder (config.model_preset "bench-1b"): big enough
    # that the bench measures the MXU and HBM, not the host link (the r1
    # 45M model ran at <1% MFU — VERDICT r1 item 1).  Random weights (no
    # egress) — throughput-identical to a trained model of this shape.
    # LMRS_BENCH_MODEL: A/B hook (e.g. "tiny" for a CPU smoke run of the
    # bench harness itself; the driver always runs the default on the chip)
    model_name = env_str("LMRS_BENCH_MODEL", "bench-1b")
    model = model_preset(model_name)
    cfg = PipelineConfig(
        # 1400-token chunks: chunk body (1250) + context header (150) + the
        # ~470-byte map template stay under the scheduler's truncation
        # limit max_seq_len - max_tokens = 1920, so no map prompt is
        # middle-truncated mid-run (at 1600 ~40% of prompts were)
        chunk=ChunkConfig(max_tokens_per_chunk=1400, context_tokens=150,
                          overlap_tokens=0, tokenizer="byte"),
        # Dispatch sizing for a ~250 ms-RTT tunneled chip (docs/PERF.md):
        # 24 slots, decode_block == max_tokens (whole decode in one
        # dispatch), prefill_chunk > max prompt (one fresh dispatch,
        # packed), page_size 512 (decode was DMA-latency-bound on page
        # fetches), num_pages=1 -> worst-case pool floor sizing.
        # quantize="int8": ABBA-measured +5.9-7.1% on decode-heavy waves at
        # this scale (weight stream halves; docs/PERF.md round 2/3).
        # kv_quantize="int8": +3.9% more (KV walk bytes halve, capacity
        # doubles; docs/PERF.md round 3).  The LIBRARY defaults stay bf16 —
        # int8 weights/KV are quality tradeoffs a throughput bench need not
        # pay but a user must opt into.
        # tokenizer pinned to byte: the 8B preset carries the real 128k
        # vocabulary (the LM head's true byte share), which would otherwise
        # flip the engine's default-tokenizer heuristic off byte.
        # LMRS_BENCH_SLOTS: page-pool headroom knob for the 8B preset
        # (24 slots x 2048 x 64 KB/token int8 = 3.2 GB worst-case pool on
        # top of ~8.6 GB weights; the driver default stays 24).
        # page_size: 512 was the r4 sweep's optimum for bf16-page DMAs;
        # int8 KV halves page bytes, and at the 8B shape the r5 split
        # measured 1024 −7% per step at the bench's ~1.8k-token live mix
        # (the DMA-issue-per-byte argument, docs/PERF.md round 5).  Short-
        # context serving configs should stay at 512 (page-quantized reads
        # dominate there); this is the bench preset's live range talking.
        engine=EngineConfig(backend="jax", max_tokens=128,
                            max_batch_slots=env_int(
                                "LMRS_BENCH_SLOTS", 24, lo=1),
                            tokenizer="byte",
                            retry_delay=0.0, seed=0,
                            page_size=1024 if model_name == "bench-8b" else 512,
                            num_pages=1, decode_block=128, prefill_chunk=4096,
                            quantize="int8", kv_quantize="int8"),
        model=model,
        reduce=ReduceConfig(max_tokens_per_batch=6000),
    )
    s = TranscriptSummarizer(cfg)

    # Warm-up outside the timed region: the FULL fixture once, so every
    # shape the timed reps use — full decode slots, packed prefill at the
    # capped bucket set, every page-window bucket the steady-state reaches,
    # the compact-batch drain, and the whole hierarchical reduce tree — is
    # compiled by construction.  (r3's 900-segment warmup missed the
    # full-run shapes and rep 1 ran ~2x slow on mid-rep compiles —
    # VERDICT r3 weak #1.)
    s.summarize(transcript)

    # Device-level roofline on the live engine (RTT-amortized chains).
    # Failure-isolated: the auxiliary detail must never cost the headline.
    sched = s.executor.engine._scheduler
    try:
        roofline = sched.roofline_microbench()
    except Exception as e:  # pragma: no cover - chip-side failure path
        print(f"roofline microbench failed: {e!r}", file=sys.stderr)
        roofline = {"roofline_error": str(e)[:200]}
    # multi-row page walk attribution: per-row kernel cost grouped vs
    # per-row (RTT-amortized chains; {} off-TPU) — the measured, not
    # asserted, per-row gain the grouped dispatch buys
    try:
        roofline.update(sched.rowcost_microbench())
    except Exception as e:  # pragma: no cover - chip-side failure path
        print(f"rowcost microbench failed: {e!r}", file=sys.stderr)
        roofline["rowcost_error"] = str(e)[:200]

    # Timed region, repeated: the tunneled link's weather produces 2-7x
    # run-to-run spread on identical code; the median + per-rep values let
    # the judge tell a real regression from a bad link day.
    # Latency samples reset here so warmup's compile-time dispatch gaps
    # (orders of magnitude over steady state) don't pollute the
    # percentiles; counter metrics are windowed via the snapshot below.
    sched.reset_latency_stats()
    metrics_before = dict(sched.metrics)
    cost_before = sched._cost.report()
    anatomy_before = sched.anatomy_snapshot()
    reps = env_int("LMRS_BENCH_REPS", 3, lo=1)
    rep_rows = _partial_reps  # shared with the watchdog (see start_watchdog)
    for _ in range(reps):
        tokens_before = s.executor.total_tokens_used
        failed_before = s.executor.failed_requests
        t0 = time.time()
        stats = s.summarize(transcript)
        wall = time.time() - t0
        rep_rows.append({
            "chunks_per_sec": round(stats["num_chunks"] / wall, 3),
            "wall_s": round(wall, 2),
            "map_s": round(stats["stage_times"].get("map", 0.0), 2),
            "reduce_s": round(stats["stage_times"].get("reduce", 0.0), 2),
            "total_tokens": stats["total_tokens_used"] - tokens_before,
            "failed": stats["failed_requests"] - failed_before,
            "num_chunks": stats["num_chunks"],
        })

    value, detail = summarize_reps(rep_rows)
    detail.update({
        "model": model.name,
        "params_m": round(_param_count_m(sched.params), 1),
        "backend": "jax",
        **roofline,
        **_scheduler_window(sched, metrics_before),
        # request-cost ledger over the timed window (obs/ledger.py):
        # windowed per-tenant device-seconds + goodput, and the host's
        # burn-rate SLO state at capture — attribution rides every BENCH
        # artifact next to the latency it explains
        "cost": sched._cost.report(cost_before),
        "slo": _slo_summary(sched.slo_report()),
    })
    # windowed step anatomy (ISSUE 18, obs/anatomy.py): named host
    # segments + ragged-span bucket economics over the timed reps only —
    # the block perf_sentry's anatomy.host_overhead_us_step /
    # anatomy.rpa_pad_waste_ratio columns resolve against.  Omitted (not
    # enabled:false) under LMRS_ANATOMY=0, wire-parity rule.
    if sched._an.enabled:
        detail["anatomy"] = sched.anatomy_report(anatomy_before)
    # live-vs-offline agreement (ISSUE 8 acceptance): the live attribution
    # gauges gathered DURING the timed reps against the RTT-amortized
    # roofline probe — rel = live/offline - 1 (within ±0.05 = agreeing)
    pa = detail.get("perf_attribution") or {}
    cmp_block = {}
    live_mfu = (pa.get("prefill_mfu") or {}).get("p50")
    if live_mfu and detail.get("model_flops_utilization"):
        cmp_block["prefill_mfu_rel"] = round(
            live_mfu / detail["model_flops_utilization"] - 1.0, 3)
    live_hbm = (pa.get("decode_hbm_util") or {}).get("p50")
    if live_hbm and detail.get("hbm_bw_utilization"):
        cmp_block["decode_hbm_rel"] = round(
            live_hbm / detail["hbm_bw_utilization"] - 1.0, 3)
    if cmp_block:
        detail["live_vs_roofline"] = cmp_block
    return float(value), detail


def _slo_summary(doc: dict) -> dict:
    """Compact SLO block for bench detail: state + per-spec burn rates
    (the full windows live on /healthz; the artifact needs the verdict
    and the why, not the raw series)."""
    return {
        "enabled": doc.get("enabled", False),
        "state": doc.get("state", "ok"),
        "specs": {name: {"state": s.get("state"),
                         "burn_fast": s.get("burn_fast"),
                         "burn_slow": s.get("burn_slow")}
                  for name, s in (doc.get("specs") or {}).items()},
    }


def _scheduler_window(sched, before: dict) -> dict:
    """Scheduler-level detail over the timed reps only (VERDICT r4 items
    2 and 5): decode occupancy, stall/preemption counts, the
    prefill/decode phase split, and the serving-latency percentiles —
    the e2e numbers needed to attribute any roofline-vs-e2e gap from the
    bench artifact alone, without rerunning a one-off script."""
    m = sched.metrics
    d_disp = m["decode_dispatches"] - before["decode_dispatches"]
    occ = ((m["occupancy_sum"] - before["occupancy_sum"]) / d_disp
           if d_disp else 0.0)
    report = sched.metrics_report()  # latency pct reset at window start
    g_disp = (m["group_dispatches"] - before["group_dispatches"])
    g_occ = ((m["group_occupancy_sum"] - before["group_occupancy_sum"])
             / g_disp if g_disp else 0.0)
    return {
        "mean_decode_occupancy": round(occ, 3),
        "decode_dispatches": d_disp,
        # multi-row kernel: configured group size and live-rows-over-group-
        # capacity occupancy over the timed window (1.0 = no padding waste)
        "decode_row_group": getattr(sched, "_row_group", 1),
        "mean_group_occupancy": round(g_occ, 3),
        "stalls": m["stalls"] - before["stalls"],
        "preemptions": m["preemptions"] - before["preemptions"],
        # device-wait vs host-bookkeeping split of the SCHEDULER wall over
        # the timed reps (map + reduce both run through the scheduler —
        # these are engine-wide, not map-only): the host share is time the
        # device sits idle between a block's fetch and the next dispatch
        # (the r5 overlap lever's attribution number)
        "sched_blocked_s": round(
            m["blocked_seconds"] - before["blocked_seconds"], 2),
        "sched_host_s": round(
            max((m["run_seconds"] - before["run_seconds"])
                - (m["blocked_seconds"] - before["blocked_seconds"]), 0.0),
            2),
        "phase_split_tokens": {
            "prefill": m["prefill_tokens"] - before["prefill_tokens"],
            "decode": m["decode_tokens"] - before["decode_tokens"],
        },
        "ttft_ms": report["ttft_ms"],
        # WAVE-LEVEL gaps (docs/PERF.md "two block-gap numbers"): on this
        # batch workload the samples include whole admission/prefill
        # waves between decode dispatches (BENCH8B_r05's 7.65 s p50 is
        # queueing, NOT serving cadence); the steady-state per-block
        # number a streaming client sees is serving_latency.py's
        # decode_block_gap_ms_steady_state.  Named distinctly so a
        # verdict can never compare the two as if they measured the same
        # thing.
        "decode_block_gap_ms_wave": report["decode_block_gap_ms"],
        # SARATHI mixed batches over the timed window (ISSUE 11): fused
        # dispatches, budget fill, and the prompt tokens that rode decode
        # steps instead of dedicated prefill waves — plus the wave gap
        # percentiles above, the MULTICHIP/BENCH tracking trio
        "mixed_batch": sched._mixed_report(before),
        # ragged-span unified dispatch (ISSUE 16): span tokens and the
        # distinct program shapes compiled over the window — the roofline
        # column perf_sentry tracks for the one-bucket-family collapse
        "rpa": sched._rpa_report(before),
        # tree speculation over the timed window (ISSUE 19): dispatches,
        # drafted nodes, and accepted tokens per dispatched row — the
        # acceptance trajectory perf_sentry tracks (spec_tree.accept_
        # per_step); zeros when speculate_k=0 or LMRS_SPEC_TREE=0
        "spec_tree": sched._spec_tree_report(before),
        # disaggregated handoff over the timed window: export/import
        # counts and orphaned pages are zero on a colocated bench by
        # construction — the block exists so MULTICHIP_* rounds that run
        # the two-tier topology can track transfer overhead against this
        # colocated baseline (capture/import latency percentiles included)
        "handoff": {
            "exports": m["handoff_exports"] - before["handoff_exports"],
            "imports": m["handoff_imports"] - before["handoff_imports"],
            "orphaned_pages": (m["handoff_orphaned_pages"]
                               - before["handoff_orphaned_pages"]),
            "pinned_pages": m["handoff_pinned_pages"],
            "capture_ms": sched._h_handoff_capture.percentile_report(),
            "import_ms": sched._h_handoff_import.percentile_report(),
        },
        # shared-prefix KV cache over the timed reps: hit rate across
        # admissions and the prompt tokens whose prefill was skipped
        # entirely (the map preamble re-use win; engine/prefix_cache.py)
        "prefix_cache": _prefix_window(m, before),
        # host-RAM spill tier over the timed window (engine/host_kv.py):
        # zero on a roomy-pool bench by construction — the block exists
        # so pressure rounds (budgeted num_pages) can track the
        # spill/prefetch traffic the tier converts re-prefills into
        "host_kv": sched._host_kv_report(before),
        # live per-phase roofline attribution (obs/perf.py): MFU / HBM
        # utilization / step-gap percentiles from the serving path's own
        # dispatch walls — what future BENCH_r* rounds record alongside
        # chunks/s, and the numbers the offline roofline block above is
        # checked against (live_vs_roofline)
        "perf_attribution": sched.perf_attribution_report(),
    }


def _prefix_window(m: dict, before: dict) -> dict:
    queries = m["prefix_queries"] - before["prefix_queries"]
    hits = m["prefix_hits"] - before["prefix_hits"]
    saved = m["prefix_tokens_reused"] - before["prefix_tokens_reused"]
    return {
        "hit_rate": round(hits / queries, 3) if queries else 0.0,
        "hits": hits,
        "queries": queries,
        "tokens_reused": saved,
        "prefill_tokens_saved": saved,
    }


def main() -> int:
    import argparse

    # minimal flag surface (the driver runs bench.py bare; --trace-out /
    # LMRS_TRACE_OUT opt into lifecycle tracing, --no-trace is the
    # overhead-A/B control) — unknown args are ignored, not fatal
    ap = argparse.ArgumentParser(add_help=False)
    ap.add_argument("--trace-out",
                    default=env_str("LMRS_TRACE_OUT") or None)
    ap.add_argument("--no-trace", action="store_true")
    args, _ = ap.parse_known_args()
    trace_out = None if args.no_trace else args.trace_out

    deadline = env_float("LMRS_BENCH_DEADLINE_S", 1800.0, lo=1.0)
    start_watchdog(deadline)

    ok, probe_log = acquire_backend()
    if not ok:
        emit(0.0, {"error": f"backend unavailable after retries: {probe_log}"})
        return 0
    try:
        value, detail = run_bench(trace_out)
        detail["backend_probe"] = probe_log
        emit(value, detail)
    except Exception as e:  # noqa: BLE001 - artifact > traceback
        import traceback
        traceback.print_exc()
        # same salvage as the watchdog: a transient device error after
        # completed reps must not zero out measured data
        emit_salvage(f"{type(e).__name__}: {e}"[:400])
    finally:
        # trace salvage mirrors the rep salvage above: whatever the ring
        # buffer captured before a failure is still a diagnosable artifact
        if trace_out:
            from lmrs_tpu.obs import export_current

            n, err = export_current(trace_out)
            print(f"wrote {n} trace events to {trace_out}" if err is None
                  else f"could not write trace {trace_out}: {err}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
